"""Elastic pod membership units: epoch leases + zombie fencing
(resilience/coordinator.py, cluster/store.ShardedSignatureStore), the
MembershipLedger's elastic re-deal, the PeerMonitor replay guard and
epoch-scoped latch, the quant-drop degradation rung, and the
epoch-tagged manifest merge — everything here is in-process and fast;
the real 2-process zombie / leader-promotion runs live in
tests/test_pod_chaos.py (slow) and the CI fault-matrix ``zombie`` /
``leader-loss-promote`` seats."""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from tse1m_tpu.cluster.store import ShardedSignatureStore, row_digests
from tse1m_tpu.observability import pop_degradation_events
from tse1m_tpu.observability.merge import (fragment_manifest_path,
                                           merge_run_manifests)
from tse1m_tpu.resilience.coordinator import (HeartbeatWriter,
                                              LeaseSupersededError,
                                              MembershipLedger, PeerMonitor,
                                              acquire_lease, heartbeat_path,
                                              read_lease, verify_lease,
                                              write_lease)

POLICY = {"n_hashes": 32, "seed": 13, "quant_bits": 0}


# -- heartbeat replay guard ---------------------------------------------------


def test_monitor_rejects_nonce_rollback(tmp_path):
    """A stale heartbeat file replaying an ALREADY-SEEN nonce must not
    resurrect a host — only a genuinely new nonce counts as an advance."""
    d = str(tmp_path)
    w = HeartbeatWriter(d, 1)
    w.beat_once()
    with open(heartbeat_path(d, 1)) as f:
        stale = f.read()  # nonce A, seq 1
    mon = PeerMonitor(d, n_processes=2, process_id=0, timeout_s=0.3)
    assert mon.poll() == []  # nonce A observed
    w2 = HeartbeatWriter(d, 1)  # restarted peer: nonce B
    w2.beat_once()
    assert mon.poll() == []  # nonce B is new: advance
    # rollback: the stale nonce-A file resurfaces (restored backup / NFS
    # cache) — it must NOT read as an advance, so the host times out
    from tse1m_tpu.utils.atomic import atomic_write

    with atomic_write(heartbeat_path(d, 1)) as f:
        f.write(stale)
    time.sleep(0.45)
    assert mon.poll() == [1]


def test_monitor_rejects_seq_regression(tmp_path):
    """A regressed seq under the current nonce is a stale file, not a
    live beat."""
    d = str(tmp_path)
    w = HeartbeatWriter(d, 1)
    w.beat_once()
    w.beat_once()
    w.beat_once()  # seq 3
    mon = PeerMonitor(d, n_processes=2, process_id=0, timeout_s=0.3)
    assert mon.poll() == []
    # regress the file to seq 1 under the SAME nonce
    with open(heartbeat_path(d, 1)) as f:
        rec = json.load(f)
    from tse1m_tpu.utils.atomic import atomic_write

    rec["seq"] = 1
    with atomic_write(heartbeat_path(d, 1)) as f:
        json.dump(rec, f)
    time.sleep(0.45)
    assert mon.poll() == [1]


def test_monitor_epoch_scoped_latch_readmits_new_nonce(tmp_path):
    """Lost in epoch N, alive in epoch N+1 — but only via a NEW nonce;
    the stale file stays dead across the epoch boundary."""
    d = str(tmp_path)
    w = HeartbeatWriter(d, 1)
    w.beat_once()
    mon = PeerMonitor(d, n_processes=2, process_id=0, timeout_s=0.3)
    mon.poll()
    time.sleep(0.45)
    assert mon.poll() == [1]          # lost in epoch 0
    w.beat_once()
    assert mon.poll() == [1]          # latched within the epoch
    assert mon.advance_epoch() == 1
    assert mon.poll() == []           # fresh grace window in epoch 1
    HeartbeatWriter(d, 1).beat_once()  # NEW nonce: genuinely re-admitted
    time.sleep(0.45)
    assert mon.poll() == []
    assert mon.ever_lost() == [1]     # history keeps the epoch-0 loss


def test_monitor_epoch_advance_stale_file_times_out_again(tmp_path):
    d = str(tmp_path)
    w = HeartbeatWriter(d, 1)
    w.beat_once()
    mon = PeerMonitor(d, n_processes=2, process_id=0, timeout_s=0.3)
    mon.poll()
    time.sleep(0.45)
    assert mon.poll() == [1]
    mon.advance_epoch()
    # nothing new on disk: the old nonce's file cannot resurrect the
    # host in the new epoch either
    time.sleep(0.45)
    assert mon.poll() == [1]


# -- membership ledger --------------------------------------------------------


def test_ledger_fresh_bootstrap_matches_modulo_deal(tmp_path):
    led = MembershipLedger(str(tmp_path), n_ranges=4)
    rec = led.bootstrap([0, 1], "n0")
    assert rec["epoch"] == 0 and rec["moved"] == []
    assert rec["owners"] == {0: 0, 1: 1, 2: 0, 3: 1}  # == r % nproc


def test_ledger_same_members_keeps_epoch_and_owners(tmp_path):
    led = MembershipLedger(str(tmp_path), n_ranges=2)
    a = led.bootstrap([0, 1], "n0")
    b = led.bootstrap([0, 1], "n1")
    assert b["epoch"] == a["epoch"] == 0
    assert b["owners"] == a["owners"] and b["moved"] == []
    assert b["nonce"] == "n1"


def test_ledger_loss_advance_moves_only_lost_ranges(tmp_path):
    led = MembershipLedger(str(tmp_path), n_ranges=4)
    led.bootstrap([0, 1], "n0")
    pop_degradation_events()
    rec = led.advance([0], "n1", reason="host_lost")
    assert rec["epoch"] == 1
    assert rec["owners"] == {0: 0, 1: 0, 2: 0, 3: 0}
    assert rec["moved"] == [1, 3]  # only the lost host's ranges moved
    kinds = [e["kind"] for e in pop_degradation_events()]
    assert "epoch_advance" in kinds


def test_ledger_recovery_readmits_with_minimal_moves(tmp_path):
    led = MembershipLedger(str(tmp_path), n_ranges=4)
    led.bootstrap([0, 1], "n0")
    led.advance([0], "n1", reason="host_lost")   # epoch 1: all -> 0
    pop_degradation_events()
    rec = led.bootstrap([0, 1], "n2")            # host 1 recovered
    assert rec["epoch"] == 2
    # elastic: process 0 keeps its balanced share; only the overflow
    # re-deals to the re-admitted member
    assert sorted(rec["moved"]) == [r for r, o in rec["owners"].items()
                                    if o == 1]
    assert sum(1 for o in rec["owners"].values() if o == 0) == 2
    assert sum(1 for o in rec["owners"].values() if o == 1) == 2
    kinds = [e["kind"] for e in pop_degradation_events()]
    assert "epoch_advance" in kinds and "host_readmitted" in kinds


def test_ledger_epoch_is_monotonic_across_changes(tmp_path):
    led = MembershipLedger(str(tmp_path), n_ranges=2)
    epochs = [led.bootstrap([0, 1], "a")["epoch"],
              led.advance([1], "b", reason="host_lost")["epoch"],
              led.bootstrap([0, 1], "c")["epoch"]]
    assert epochs == sorted(epochs) and len(set(epochs)) == 3


def test_ledger_wait_for_adopts_matching_nonce(tmp_path):
    led = MembershipLedger(str(tmp_path), n_ranges=2)
    led.bootstrap([0, 1], "want")
    rec = led.wait_for("want", timeout_s=1.0)
    assert rec["nonce"] == "want"
    with pytest.raises(TimeoutError):
        led.wait_for("other", timeout_s=0.3)


# -- leases -------------------------------------------------------------------


def test_lease_acquire_verify_roundtrip(tmp_path):
    root = str(tmp_path)
    acquire_lease(root, 0, epoch=0, owner=1, nonce="n")
    assert read_lease(root, 0) == {"range": 0, "epoch": 0, "owner": 1,
                                   "nonce": "n"}
    verify_lease(root, 0, epoch=0, owner=1, nonce="n")  # no raise


def test_lease_superseded_by_later_epoch(tmp_path):
    root = str(tmp_path)
    acquire_lease(root, 0, epoch=0, owner=1, nonce="old")
    acquire_lease(root, 0, epoch=1, owner=0, nonce="new")  # re-deal
    with pytest.raises(LeaseSupersededError) as ei:
        verify_lease(root, 0, epoch=0, owner=1, nonce="old")
    assert ei.value.current["epoch"] == 1
    with pytest.raises(LeaseSupersededError):
        acquire_lease(root, 0, epoch=0, owner=1, nonce="old")


def test_lease_same_epoch_conflicting_owner_refuses(tmp_path):
    root = str(tmp_path)
    acquire_lease(root, 0, epoch=2, owner=0, nonce="a")
    with pytest.raises(LeaseSupersededError):
        acquire_lease(root, 0, epoch=2, owner=1, nonce="b")
    # same owner, fresh run nonce: a clean re-run refreshes
    acquire_lease(root, 0, epoch=2, owner=0, nonce="c")
    verify_lease(root, 0, epoch=2, owner=0, nonce="c")


def test_lease_missing_or_wrong_nonce_fences(tmp_path):
    root = str(tmp_path)
    with pytest.raises(LeaseSupersededError):
        verify_lease(root, 3, epoch=0, owner=0, nonce="n")  # absent
    write_lease(root, 3, epoch=0, owner=0, nonce="other-run")
    with pytest.raises(LeaseSupersededError):
        verify_lease(root, 3, epoch=0, owner=0, nonce="n")


# -- lease-fenced sharded store ----------------------------------------------


def _items(n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(1, 2**20, size=(n, 16), dtype=np.uint32)


def _membership(epoch, owners, nonce="n", moved=()):
    return {"epoch": epoch, "nonce": nonce, "owners": owners,
            "members": sorted(set(owners.values())),
            "moved": list(moved)}


def test_sharded_store_membership_ownership_and_leases(tmp_path):
    root = os.path.join(str(tmp_path), "pod")
    m0 = _membership(0, {0: 0, 1: 1})
    s0 = ShardedSignatureStore(root, POLICY, n_processes=2, process_id=0,
                               n_ranges=2, membership=m0)
    assert s0.owned == [0]
    assert read_lease(root, 0)["owner"] == 0  # acquired at open
    assert read_lease(root, 1) is None        # not ours to take
    items = _items(100)
    d = row_digests(items)
    sigs = np.arange(100 * 32, dtype=np.uint32).reshape(100, 32)
    assert s0.append(d, sigs) > 0  # valid lease: appends fine


def test_zombie_append_self_fences_with_zero_writes(tmp_path):
    """The tentpole contract, in-process: a writer holding an epoch-0
    lease whose range is re-dealt at epoch 1 must raise
    LeaseSupersededError at append, demote to read-only, write ZERO
    rows, and record the lease_superseded degradation event."""
    root = os.path.join(str(tmp_path), "pod")
    zombie = ShardedSignatureStore(root, POLICY, n_processes=2,
                                   process_id=1, n_ranges=2,
                                   membership=_membership(
                                       0, {0: 0, 1: 1}, nonce="z"))
    # survivor advances the epoch and takes over range 1
    survivor = ShardedSignatureStore(root, POLICY, n_processes=1,
                                     process_id=0, n_ranges=2,
                                     membership=_membership(
                                         1, {0: 0, 1: 0}, nonce="s",
                                         moved=[1]))
    assert survivor.owned == [0, 1]
    assert 1 in survivor.reassigned_ranges
    items = _items(200, seed=3)
    d = row_digests(items)
    sigs = np.arange(200 * 32, dtype=np.uint32).reshape(200, 32)
    pop_degradation_events()
    with pytest.raises(LeaseSupersededError):
        zombie.append(d, sigs)
    assert zombie.fenced and zombie.owned == []
    # zero appends: the superseded range holds exactly what it held
    # (a legacy open without membership reads without touching leases)
    reader = ShardedSignatureStore(root, POLICY, n_processes=1,
                                   process_id=0)
    assert reader.range_store(1).n_rows == 0
    events = pop_degradation_events()
    assert any(e["kind"] == "lease_superseded" for e in events)
    # a fenced store appends nothing even if asked again
    assert zombie.append(d, sigs) == 0
    # the survivor's own append still works (it holds the epoch-1 lease)
    assert survivor.append(d, sigs) > 0


def test_legacy_writer_against_leased_root_fences(tmp_path):
    """An un-leased (legacy/modulo) open against a root an epoch plane
    governs must fence at append — it cannot prove tenure."""
    root = os.path.join(str(tmp_path), "pod")
    ShardedSignatureStore(root, POLICY, n_processes=1, process_id=0,
                          n_ranges=2,
                          membership=_membership(0, {0: 0, 1: 0}))
    legacy = ShardedSignatureStore(root, POLICY, n_processes=1,
                                   process_id=0)
    items = _items(50, seed=5)
    with pytest.raises(LeaseSupersededError):
        legacy.append(row_digests(items),
                      np.zeros((50, 32), np.uint32))
    assert legacy.fenced


def test_unleased_root_legacy_append_still_works(tmp_path):
    """No membership, no lease files: the pre-epoch contract holds for
    direct opens (tests, scrub, fresh single-host-style roots)."""
    root = os.path.join(str(tmp_path), "pod")
    s = ShardedSignatureStore(root, POLICY, n_processes=1, process_id=0,
                              n_ranges=2)
    items = _items(50, seed=7)
    assert s.append(row_digests(items),
                    np.zeros((50, 32), np.uint32)) > 0


# -- epoch-tagged manifest merge (mid-run membership change) ------------------


def _fragment(ok, counts, steps, epoch=None):
    frag = {"ok": ok, "degradation_counts": counts, "steps": steps,
            "summary": {"ok": len(steps)}, "started_at": "t",
            "wall_seconds": 1.0}
    if epoch is not None:
        frag["epoch"] = epoch
    return frag


def test_merge_tags_steps_with_epochs_and_sums_once(tmp_path):
    """A host that re-admits in epoch N+1 appears process-tagged WITH
    its epoch, and degradation_counts sums across epochs without
    double-counting (each fragment's events are counted exactly once)."""
    d = str(tmp_path)
    with open(fragment_manifest_path(d, 0), "w") as f:
        json.dump(_fragment(True, {"host_lost": 1, "epoch_advance": 1},
                            [{"step": "cluster", "status": "ok"}],
                            epoch=0), f)
    with open(fragment_manifest_path(d, 1), "w") as f:
        json.dump(_fragment(True, {"shard_range_reassigned": 2,
                                   "epoch_advance": 1},
                            [{"step": "cluster", "status": "ok"}],
                            epoch=1), f)
    merged = merge_run_manifests(d, 2)
    assert merged["degradation_counts"] == {"host_lost": 1,
                                            "epoch_advance": 2,
                                            "shard_range_reassigned": 2}
    by_pid = {s["process"]: s for s in merged["steps"]}
    assert by_pid[0]["epoch"] == 0 and by_pid[1]["epoch"] == 1
    assert merged["pod"]["epochs"] == {"0": 0, "1": 1}
    assert merged["pod"]["epoch"] == 1


def test_merge_without_epochs_stays_compatible(tmp_path):
    d = str(tmp_path)
    for pid in (0, 1):
        with open(fragment_manifest_path(d, pid), "w") as f:
            json.dump(_fragment(True, {}, [{"step": "s",
                                            "status": "ok"}]), f)
    merged = merge_run_manifests(d, 2)
    assert merged["pod"]["epoch"] is None
    assert all("epoch" not in s for s in merged["steps"])


# -- pod pipeline under membership (single-process, in-process) ---------------


def test_pod_pipeline_epoch_advances_on_readmission(tmp_path):
    """Run the pod pipeline 2-process-shaped ledger history, then a solo
    resume: the ledger advances and every range re-deals to the solo
    process; labels equal a fresh run's."""
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.cluster.pipeline import cluster_sessions_pod, \
        last_run_info
    from tse1m_tpu.data.synth import synth_session_sets

    items, _ = synth_session_sets(300, set_size=16, seed=13)
    root = os.path.join(str(tmp_path), "pod_store")
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                           sig_store=root)
    # seed a 2-member epoch history (as if a 2-process run created it)
    MembershipLedger(os.path.join(root, "pod"), 1).bootstrap([0, 1], "h0")
    labels = cluster_sessions_pod(items, 300, params)
    assert last_run_info["pod_epoch"] == 1  # advanced at readmission
    labels2 = cluster_sessions_pod(items, 300, params)
    np.testing.assert_array_equal(labels, labels2)
    assert last_run_info["cache_hit_rate"] == 1.0
    assert last_run_info["pod_epoch"] == 1  # unchanged members: no advance
