"""Regenerate the pinned-value golden CSVs (tests/goldens/synth8/).

The reference ships its published numbers as the regression oracle
(data/result_data/rq1/rq1_detection_rate_stats.csv, first data row
``1,878,297`` — rq1_detection_rate.py:354-412); its real dump is absent
from the snapshot, so the rebuild pins its OWN values instead: one
frozen-seed synthetic study, run end to end, with the six RQ artifact
CSVs committed.  tests/test_value_goldens.py asserts both engines still
reproduce these values — numeric drift that format checks cannot catch
fails CI.

Regenerate (only when an intentional semantic change shifts values)::

    python tests/goldens/generate_goldens.py

Goldens are produced by the PANDAS engine — the reference-semantics
oracle; the device engine must match it to float tolerance anyway.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "synth8")

# Frozen study: every value downstream derives from this spec + seed.
SPEC = dict(n_projects=8, days=400, seed=42, fuzz_rate=1.2,
            ineligible_fraction=0.0)

# The committed artifact set — the value-dense CSV of every RQ.
FILES = [
    "rq1/rq1_detection_rate_stats.csv",
    "rq1/rq1_raw_issues_for_analysis.csv",
    "rq2/coverage_by_session_index.csv",
    "rq3/all_coverage_change_analysis.csv",
    "rq3/detected_coverage_changes.csv",
    "rq4/bug/rq4_g1_g2_detection_trend.csv",
    "rq4/bug/rq4_gc_introduction_iteration.csv",
    "rq4/coverage/g2_g1_trend_stats.csv",
]

_DRIVER = """
import os
from tse1m_tpu.cli import main
from tse1m_tpu.config import load_config
from tse1m_tpu.data.synth import SynthSpec, generate_study
from tse1m_tpu.db.connection import DB

spec = SynthSpec(**{spec!r})
study = generate_study(spec)
cfg = load_config()
db = DB(config=cfg).connect()
study.to_db(db)
study.corpus_analysis.to_csv(os.environ["TSE1M_CORPUS_CSV"], index=False)
db.closeConnection()
raise SystemExit(main(["all"]))
"""


def run_frozen_study(result_dir: str, backend: str, workdir: str) -> None:
    """Build the frozen synth study in ``workdir`` and run all six RQ
    drivers with ``backend``, artifacts under ``result_dir``."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "TSE1M_ENGINE": "sqlite",
        "TSE1M_SQLITE_PATH": os.path.join(workdir, "golden.sqlite"),
        "TSE1M_RESULT_DIR": result_dir,
        "TSE1M_BACKEND": backend,
        # The reference's TEST_MODE (rq1_detection_rate.py:20): an
        # 8-project study needs the >=100-project filter dropped to 1 or
        # every per-iteration table is empty.
        "TSE1M_TEST_MODE": "1",
        "TSE1M_CORPUS_CSV": os.path.join(workdir,
                                         "project_corpus_analysis.csv"),
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER.format(spec=SPEC)],
        cwd=workdir, env=env, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"golden study run failed:\n{proc.stdout[-2000:]}"
            f"\n{proc.stderr[-2000:]}")


def main() -> int:
    with tempfile.TemporaryDirectory() as d:
        result = os.path.join(d, "result")
        run_frozen_study(result, "pandas", d)
        for rel in FILES:
            src = os.path.join(result, rel)
            dst = os.path.join(GOLDEN_DIR, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copyfile(src, dst)
            print(f"golden: {rel}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
