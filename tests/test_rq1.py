"""RQ1: backend parity (pandas vs jax), oracle correctness, artifacts."""

import os

import numpy as np
import pytest

from tse1m_tpu.analysis.common import StudyContext, limit_date_ns
from tse1m_tpu.analysis.rq1 import run_rq1
from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.config import Config, RESULT_OK
from tse1m_tpu.data.columnar import StudyArrays


LIMIT = "2026-01-01"


@pytest.fixture(scope="module")
def arrays(study_db):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT)
    return StudyArrays.from_db(study_db, cfg)


@pytest.fixture(scope="module")
def limit_ns():
    return int(np.datetime64(LIMIT, "ns").astype(np.int64))


@pytest.mark.parametrize("mesh", [None, "auto"],
                         ids=["single-device", "mesh"])
def test_backend_parity(arrays, limit_ns, mesh):
    res_pd = PandasBackend().rq1_detection(arrays, limit_ns, min_projects=2)
    res_jx = JaxBackend(mesh=mesh).rq1_detection(arrays, limit_ns,
                                                 min_projects=2)
    np.testing.assert_array_equal(res_pd.iterations, res_jx.iterations)
    np.testing.assert_array_equal(res_pd.total_projects, res_jx.total_projects)
    np.testing.assert_array_equal(res_pd.detected_counts, res_jx.detected_counts)
    np.testing.assert_array_equal(res_pd.iteration_of_issue, res_jx.iteration_of_issue)
    np.testing.assert_array_equal(res_pd.link_idx, res_jx.link_idx)


def test_oracle_reference_semantics(arrays, limit_ns, study_db):
    """Brute-force re-derivation of the reference's rules straight from DB
    rows (independent of the columnar layer)."""
    res = PandasBackend().rq1_detection(arrays, limit_ns, min_projects=1)
    pidx = arrays.project_index()

    rows = study_db.query(
        "SELECT project, timecreated, result FROM buildlog_data "
        "WHERE build_type='Fuzzing' ORDER BY project, timecreated")
    import pandas as pd

    builds_by_proj = {}
    for proj, tc, result in rows:
        builds_by_proj.setdefault(proj, []).append(
            (pd.Timestamp(tc).value, result))

    # Phase-1 totals: iteration k slot per project with >= k builds
    # (only eligible projects).
    totals = {}
    for proj in arrays.projects:
        for k in range(1, len(builds_by_proj.get(proj, [])) + 1):
            totals[k] = totals.get(k, 0) + 1

    # Issue mapping with reference rules.
    detected = {}
    irows = study_db.query(
        "SELECT project, rts FROM issues WHERE status IN ('Fixed','Fixed (Verified)') "
        "AND rts < ? ORDER BY project, rts, number", (LIMIT,))
    checked = 0
    for proj, rts in irows:
        if proj not in pidx:
            continue
        t = pd.Timestamp(rts).value
        blds = builds_by_proj.get(proj, [])
        iteration = sum(1 for bt, _ in blds if t > bt)
        linked = any(bt < t and r in RESULT_OK and bt < limit_ns for bt, r in blds)
        if linked and iteration > 0:
            detected.setdefault(iteration, set()).add(proj)
        checked += 1
    assert checked == len(arrays.issues)

    got_totals = dict(zip(res.iterations.tolist(), res.total_projects.tolist()))
    assert got_totals == {k: v for k, v in totals.items()}
    got_detected = dict(zip(res.iterations.tolist(), res.detected_counts.tolist()))
    for k in got_totals:
        assert got_detected[k] == len(detected.get(k, set())), f"iteration {k}"


@pytest.mark.parametrize("backend", ["pandas", "jax_tpu", "auto"])
def test_run_rq1_end_to_end(backend, study_db, tmp_path):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT, backend=backend,
                 result_dir=str(tmp_path / backend))
    cfg.min_projects_per_iteration = 2
    out = run_rq1(cfg, db=study_db)
    stats = out["stats_csv"]
    assert os.path.exists(stats)
    with open(stats) as f:
        header = f.readline().strip()
    assert header == "Iteration,Total_Projects,Detected_Projects_Count"
    assert os.path.exists(os.path.join(os.path.dirname(stats), "rq1_detection_rate.pdf"))
    assert os.path.exists(os.path.join(os.path.dirname(stats), "rq1_manifest.json"))


def test_backend_parity_zero_issues(arrays, limit_ns):
    """Phase-1 totals must be computed even with no issues (the reference
    computes them independently of issues, rq1:189-201)."""
    import copy

    a = copy.copy(arrays)
    from tse1m_tpu.data.columnar import Segmented

    a.issues = Segmented(
        offsets=np.zeros(arrays.n_projects + 1, dtype=np.int64),
        columns={"time_ns": np.empty(0, np.int64),
                 "number": np.empty(0, object),
                 "status": np.empty(0, object),
                 "crash_type": np.empty(0, object)})
    res_pd = PandasBackend().rq1_detection(a, limit_ns, min_projects=2)
    res_jx = JaxBackend().rq1_detection(a, limit_ns, min_projects=2)
    assert len(res_pd.iterations) > 0
    np.testing.assert_array_equal(res_pd.iterations, res_jx.iterations)
    np.testing.assert_array_equal(res_pd.total_projects, res_jx.total_projects)
    assert res_pd.detected_counts.sum() == res_jx.detected_counts.sum() == 0


def test_backend_parity_subsecond_ordering():
    """Builds and issues within the same second must order by nanoseconds on
    both backends (two-lane int32 comparison on device)."""
    from tse1m_tpu.data.columnar import Segmented, StudyArrays

    base = int(np.datetime64("2024-03-01T12:00:00", "ns").astype(np.int64))
    ms = 1_000_000
    build_ns = np.array([base + 100 * ms, base + 500 * ms, base + 900 * ms])
    # issue at +600ms: pandas sees 2 builds strictly before.
    issue_ns = np.array([base + 600 * ms])
    arrays = StudyArrays(
        projects=["p0"],
        fuzz=Segmented(np.array([0, 3]), {
            "time_ns": build_ns,
            "name": np.array(["a", "b", "c"], object),
            "result": np.array(["Finish"] * 3, object),
            "ok": np.ones(3, bool),
            "modules_raw": np.array([""] * 3, object),
            "revisions_raw": np.array([""] * 3, object)}),
        covb=Segmented(np.array([0, 0]), {}),
        issues=Segmented(np.array([0, 1]), {
            "time_ns": issue_ns,
            "number": np.array(["1"], object),
            "status": np.array(["Fixed"], object),
            "crash_type": np.array([""], object)}),
        cov=Segmented(np.array([0, 0]), {}),
    )
    limit = int(np.datetime64("2025-01-08", "ns").astype(np.int64))
    res_pd = PandasBackend().rq1_detection(arrays, limit, min_projects=1)
    res_jx = JaxBackend().rq1_detection(arrays, limit, min_projects=1)
    assert res_pd.iteration_of_issue[0] == 2
    np.testing.assert_array_equal(res_pd.iteration_of_issue, res_jx.iteration_of_issue)
    np.testing.assert_array_equal(res_pd.link_idx, res_jx.link_idx)
    assert res_pd.link_idx[0] == 1  # the +500ms build, not the +900ms one


def test_run_rq1_backends_identical_artifacts(study_db, tmp_path):
    outs = {}
    for backend in ("pandas", "jax_tpu", "auto"):
        cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                     limit_date=LIMIT, backend=backend,
                     result_dir=str(tmp_path / ("r_" + backend)))
        cfg.min_projects_per_iteration = 2
        outs[backend] = run_rq1(cfg, db=study_db)["stats_csv"]
    from pathlib import Path

    contents = {k: Path(v).read_text() for k, v in outs.items()}
    assert contents["pandas"] == contents["jax_tpu"] == contents["auto"]
