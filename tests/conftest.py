"""Test config: force an 8-device virtual CPU mesh before JAX initialises.

This is the multi-device test strategy from SURVEY.md §4(d): mesh/pjit logic
is exercised on 8 virtual CPU devices so sharding is testable without real
TPU hardware; the driver separately dry-runs the multichip path.
"""

import os
import sys

# Force CPU even when the environment pins a real accelerator platform
# (e.g. JAX_PLATFORMS=axon exposing one TPU chip): tests exercise mesh logic
# on 8 virtual CPU devices; benchmarks use the real chip via bench.py.
# The env var alone is not enough here — the image's sitecustomize imports
# jax and registers the axon PJRT plugin before pytest starts, so we must
# also flip the already-imported config.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from tse1m_tpu.config import Config  # noqa: E402
from tse1m_tpu.db.connection import DB  # noqa: E402
from tse1m_tpu.data.synth import SynthSpec, generate_study  # noqa: E402


@pytest.fixture(scope="session")
def synth_study():
    return generate_study(SynthSpec(n_projects=16, days=420, seed=7))


@pytest.fixture(scope="session")
def study_db(synth_study, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("db") / "study.sqlite")
    cfg = Config(engine="sqlite", sqlite_path=path)
    db = DB(config=cfg).connect()
    synth_study.to_db(db)
    yield db
    db.closeConnection()


@pytest.fixture(scope="session")
def study_cfg(study_db):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path)
    # Fixture projects have 420 coverage days; keep the reference's 365-day
    # eligibility threshold meaningful.
    return cfg


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


def _git(repo, *args, env=None):
    import subprocess

    subprocess.run(["git", *args], cwd=repo, check=True, capture_output=True,
                   env=env)


def _commit(repo, message, when):
    env = dict(os.environ,
               GIT_AUTHOR_DATE=when, GIT_COMMITTER_DATE=when,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@x",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@x")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-m", message, "--allow-empty", env=env)


@pytest.fixture()
def oss_fuzz_repo(tmp_path):
    """Tiny synthetic oss-fuzz checkout: two projects with project.yaml +
    build.sh, deterministic commit times, a seed-corpus introduction."""
    repo = str(tmp_path / "oss-fuzz")
    os.makedirs(repo)
    _git(repo, "init", "-q")
    zlib = os.path.join(repo, "projects", "zlib")
    os.makedirs(zlib)
    with open(os.path.join(zlib, "project.yaml"), "w") as fh:
        fh.write("language: c\nhomepage: https://zlib.net\n"
                 "sanitizers:\n- address\n- memory\n"
                 "auto_ccs: []\nmain_repo: https://github.com/madler/zlib\n")
    with open(os.path.join(zlib, "build.sh"), "w") as fh:
        fh.write("#!/bin/bash\ncompile\n")
    _commit(repo, "add zlib", "2021-03-01T10:00:00+00:00")
    brotli = os.path.join(repo, "projects", "brotli")
    os.makedirs(brotli)
    with open(os.path.join(brotli, "project.yaml"), "w") as fh:
        fh.write("language: c++\nvendor_ccs:\n  a: 1\n")
    with open(os.path.join(brotli, "build.sh"), "w") as fh:
        fh.write("#!/bin/bash\ncp x_seed_corpus.zip $OUT/\ncompile\n")
    _commit(repo, "add brotli", "2021-04-01T10:00:00+00:00")
    with open(os.path.join(zlib, "build.sh"), "a") as fh:
        fh.write("cp zlib_seed_corpus.zip $OUT/\n")
    _commit(repo, "seed corpus for zlib", "2021-04-15T10:00:00+00:00")
    return repo
