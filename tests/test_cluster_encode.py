"""Base-delta H2D encoding (cluster/encode.py + the pipeline's encoded
path) — the round-5 attack on the north star's dominant cost, the ~25 MB/s
tunneled H2D link (BENCH_r04: 7.2 s of a 9.5 s wall moving 183 MB).

Contracts under test:
- encode/decode round-trips bit-exactly (numpy AND native encoders);
- grouping is only a heuristic: rep_of has no chains, every encoded pair
  verified within max_diffs;
- the encoded pipeline's labels are bit-identical to the unencoded
  pipeline's (hub election by original index — lsh.bucket_representatives);
- the auto policy only engages when worthwhile;
- the checkpoint/resume path survives a kill with encoding on.
"""

from __future__ import annotations

import numpy as np
import pytest

import tse1m_tpu.cluster.pipeline as pipeline_mod
from tse1m_tpu.cluster import (ClusterParams, cluster_sessions,
                               cluster_sessions_resumable)
from tse1m_tpu.cluster.checkpoint import ClusterCheckpoint
from tse1m_tpu.cluster.encode import (DeltaEncoding, _group_rows, decode_host,
                                      encode_delta)
from tse1m_tpu.data.synth import synth_session_sets

N = 4096


@pytest.fixture(scope="module")
def dup_items():
    # dup_fraction 0.6 / mean cluster 8 — the planted near-duplicate shape
    # the encoder exists for.
    return synth_session_sets(N, set_size=16, seed=21)[0]


def _encoders():
    yield "numpy", False
    from tse1m_tpu.native import group_delta_native

    if group_delta_native(np.zeros((2, 4), np.uint32), 4, 1) is not None:
        yield "native", True


@pytest.mark.parametrize("name,use_native", list(_encoders()))
def test_roundtrip_bit_exact(dup_items, name, use_native):
    enc = encode_delta(dup_items, use_native=use_native)
    assert enc is not None and enc.n_delta > 0
    np.testing.assert_array_equal(decode_host(enc), dup_items)
    # the encoding actually compresses this workload
    assert enc.wire_bytes(True) < dup_items.shape[0] * dup_items.shape[1] * 3


@pytest.mark.parametrize("name,use_native", list(_encoders()))
def test_group_invariants(dup_items, name, use_native):
    if use_native:
        from tse1m_tpu.native import group_delta_native

        rep_of = np.asarray(group_delta_native(dup_items, 16, 3))
    else:
        rep_of = _group_rows(dup_items, 16, 3)
    d = rep_of >= 0
    assert d.any()
    # no chains: a base row is never itself a delta row
    assert np.all(rep_of[rep_of[d]] == -1)
    # every encoded pair verified within the cap
    nd = (dup_items[d] != dup_items[rep_of[d]]).sum(axis=1)
    assert nd.max() <= 16


def test_roundtrip_with_wide_values():
    """Values above 2^24 (no 24-bit pack) still round-trip."""
    rng = np.random.default_rng(5)
    base = rng.integers(0, 1 << 31, size=(64, 8), dtype=np.uint32)
    items = np.repeat(base, 4, axis=0)
    mut = rng.random(items.shape) < 0.1
    items[mut] = rng.integers(0, 1 << 31, size=int(mut.sum()), dtype=np.uint32)
    enc = encode_delta(items, use_native=False)
    assert enc is not None
    np.testing.assert_array_equal(decode_host(enc), items)


def test_no_duplicates_returns_none():
    rng = np.random.default_rng(6)
    items = rng.integers(0, 1 << 24, size=(512, 16), dtype=np.uint32)
    # distinct random rows: nothing to attach (verification rejects any
    # chance key collision), so the encoder declines
    assert encode_delta(items, min_delta_fraction=0.05) is None


def test_encoded_labels_bit_identical(dup_items):
    base = ClusterParams(use_pallas="interpret", block_n=128, h2d_chunks=4,
                         encoding="pack24")
    enc = ClusterParams(use_pallas="interpret", block_n=128, h2d_chunks=4,
                        encoding="delta")
    np.testing.assert_array_equal(cluster_sessions(dup_items, enc),
                                  cluster_sessions(dup_items, base))
    assert pipeline_mod.last_run_info["encoding"] == "pack24"


def test_encoded_labels_bit_identical_raw_values():
    """Same parity when values exceed the 24-bit pack limit."""
    rng = np.random.default_rng(9)
    base_rows = rng.integers(0, 1 << 30, size=(128, 16), dtype=np.uint32)
    items = np.repeat(base_rows, 6, axis=0)
    mut = rng.random(items.shape) < 0.08
    items[mut] = rng.integers(0, 1 << 30, size=int(mut.sum()),
                              dtype=np.uint32)
    perm = rng.permutation(items.shape[0])
    items = items[perm]
    prm = dict(use_pallas="never", h2d_chunks=2)
    np.testing.assert_array_equal(
        cluster_sessions(items, ClusterParams(encoding="delta", **prm)),
        cluster_sessions(items, ClusterParams(encoding="pack24", **prm)))


def test_auto_policy_skips_small_inputs(dup_items):
    cluster_sessions(dup_items[:512],
                     ClusterParams(use_pallas="never", encoding="auto"))
    # the two-step no-pallas path ships raw uint32 — the report says so
    assert pipeline_mod.last_run_info["encoding"] == "raw"


def test_auto_policy_engages_on_large_compressible(dup_items, monkeypatch):
    monkeypatch.setattr(pipeline_mod, "_AUTO_MIN_BYTES", 1024)
    cluster_sessions(dup_items,
                     ClusterParams(use_pallas="never", encoding="auto"))
    info = pipeline_mod.last_run_info
    assert info["encoding"] == "delta"
    assert info["n_full"] + info["n_delta"] == N
    assert info["wire_mb"] <= N * 16 * 3 / 2**20


def test_resumable_encoded_matches_plain(dup_items, tmp_path):
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")
    want = cluster_sessions(dup_items, prm)
    got = cluster_sessions_resumable(dup_items, prm,
                                     checkpoint_dir=str(tmp_path / "ck"))
    np.testing.assert_array_equal(got, want)
    assert not list((tmp_path / "ck").glob("shard_*.npz"))


def test_resumable_encoded_kill_and_resume(dup_items, tmp_path, monkeypatch):
    d = str(tmp_path / "ck")
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")
    want = cluster_sessions(dup_items, prm)

    class Boom(RuntimeError):
        pass

    saved = []
    real_save = ClusterCheckpoint.save_chunk

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        saved.append(index)
        if len(saved) == 2:
            raise Boom()

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", dying_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)
    got = cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    np.testing.assert_array_equal(got, want)


def test_resumable_refuses_different_lane_split(dup_items, tmp_path,
                                                monkeypatch):
    """A resume whose encoder drew different lanes must refuse, not mix
    shards (the native/numpy encoders may legitimately group differently)."""
    d = str(tmp_path / "ck")
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")

    class Boom(RuntimeError):
        pass

    saved = []
    real_save = ClusterCheckpoint.save_chunk

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        saved.append(index)
        raise Boom()

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", dying_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)

    real_encode = pipeline_mod.encode_delta

    def other_lanes(items, **kw):
        enc = real_encode(items, **kw)
        # drop one delta row back into the full lane -> different split
        keep = np.ones(enc.n_delta, bool)
        keep[0] = False
        return _drop_delta_row(items, enc, keep)

    monkeypatch.setattr(pipeline_mod, "encode_delta", other_lanes)
    with pytest.raises(ValueError, match="different"):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)


def test_resumable_refuses_encoding_mode_change(dup_items, tmp_path,
                                                monkeypatch):
    """A delta-encoded checkpoint resumed with encoding off holds
    full-lane shards that would be misread as item-chunk shards — the
    manifest's symmetric meta comparison must refuse."""
    d = str(tmp_path / "ck")
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")

    class Boom(RuntimeError):
        pass

    real_save = ClusterCheckpoint.save_chunk

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        raise Boom()

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", dying_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)
    plain = ClusterParams(use_pallas="never", h2d_chunks=4,
                          encoding="pack24")
    with pytest.raises(ValueError, match="different"):
        cluster_sessions_resumable(dup_items, plain, checkpoint_dir=d)


def test_unknown_encoding_rejected(dup_items):
    with pytest.raises(ValueError, match="unknown encoding"):
        cluster_sessions(dup_items[:64],
                         ClusterParams(use_pallas="never", encoding="raw"))


def _drop_delta_row(items: np.ndarray, enc: DeltaEncoding,
                    keep: np.ndarray) -> DeltaEncoding:
    """Rebuild an encoding with a subset of its delta rows (test helper)."""
    is_delta = np.unpackbits(enc.mask_bits, bitorder="little")[:enc.n]
    delta_idx = np.flatnonzero(is_delta)
    new_mask = np.zeros(enc.n, bool)
    new_mask[delta_idx[keep]] = True
    full_rank = np.cumsum(~new_mask) - 1
    rows = np.repeat(np.arange(enc.n_delta), enc.counts)
    keep_flat = keep[rows]
    # original index of each kept delta row's base
    full_idx_old = np.flatnonzero(~is_delta)
    rep_orig = full_idx_old[enc.rep_in_full]
    return DeltaEncoding(
        n=enc.n, set_size=enc.set_size,
        mask_bits=np.packbits(new_mask, bitorder="little"),
        full_rows=np.ascontiguousarray(items[~new_mask]),
        rep_in_full=full_rank[rep_orig[keep]].astype(np.int32),
        counts=enc.counts[keep],
        pos_flat=enc.pos_flat[keep_flat],
        val_flat=enc.val_flat[keep_flat],
    )
