"""Base-delta H2D encoding (cluster/encode.py + the pipeline's encoded
path) — the round-5 attack on the north star's dominant cost, the ~25 MB/s
tunneled H2D link (BENCH_r04: 7.2 s of a 9.5 s wall moving 183 MB).

Contracts under test:
- encode/decode round-trips bit-exactly (numpy AND native encoders);
- grouping is only a heuristic: rep_of has no chains, every encoded pair
  verified within max_diffs;
- the encoded pipeline's labels are bit-identical to the unencoded
  pipeline's (hub election by original index — lsh.bucket_representatives);
- the auto policy only engages when worthwhile;
- the checkpoint/resume path survives a kill with encoding on.
"""

from __future__ import annotations

import numpy as np
import pytest

import tse1m_tpu.cluster.pipeline as pipeline_mod
from tse1m_tpu.cluster import (ClusterParams, cluster_sessions,
                               cluster_sessions_resumable)
from tse1m_tpu.cluster.checkpoint import ClusterCheckpoint
from tse1m_tpu.cluster.encode import (DeltaEncoding, _group_rows,
                                      chunk_wire_bits, decode_host,
                                      encode_delta, pack_bits_host,
                                      pack_chunk, pack_delta_meta,
                                      quantize_ids, unpack_bits_host,
                                      unpack_chunk_host, width_bits)
from tse1m_tpu.data.synth import synth_session_sets

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # property tests degrade to the deterministic suite
    HAVE_HYPOTHESIS = False

N = 4096


@pytest.fixture(scope="module")
def dup_items():
    # dup_fraction 0.6 / mean cluster 8 — the planted near-duplicate shape
    # the encoder exists for.
    return synth_session_sets(N, set_size=16, seed=21)[0]


def _encoders():
    yield "numpy", False
    from tse1m_tpu.native import group_delta_native

    if group_delta_native(np.zeros((2, 4), np.uint32), 4, 1) is not None:
        yield "native", True


@pytest.mark.parametrize("name,use_native", list(_encoders()))
def test_roundtrip_bit_exact(dup_items, name, use_native):
    enc = encode_delta(dup_items, use_native=use_native)
    assert enc is not None and enc.n_delta > 0
    np.testing.assert_array_equal(decode_host(enc), dup_items)
    # the encoding actually compresses this workload
    assert enc.wire_bytes(True) < dup_items.shape[0] * dup_items.shape[1] * 3


@pytest.mark.parametrize("name,use_native", list(_encoders()))
def test_group_invariants(dup_items, name, use_native):
    if use_native:
        from tse1m_tpu.native import group_delta_native

        rep_of = np.asarray(group_delta_native(dup_items, 16, 3))
    else:
        rep_of = _group_rows(dup_items, 16, 3)
    d = rep_of >= 0
    assert d.any()
    # no chains: a base row is never itself a delta row
    assert np.all(rep_of[rep_of[d]] == -1)
    # every encoded pair verified within the cap
    nd = (dup_items[d] != dup_items[rep_of[d]]).sum(axis=1)
    assert nd.max() <= 16


def test_roundtrip_with_wide_values():
    """Values above 2^24 (no 24-bit pack) still round-trip."""
    rng = np.random.default_rng(5)
    base = rng.integers(0, 1 << 31, size=(64, 8), dtype=np.uint32)
    items = np.repeat(base, 4, axis=0)
    mut = rng.random(items.shape) < 0.1
    items[mut] = rng.integers(0, 1 << 31, size=int(mut.sum()), dtype=np.uint32)
    enc = encode_delta(items, use_native=False)
    assert enc is not None
    np.testing.assert_array_equal(decode_host(enc), items)


def test_no_duplicates_returns_none():
    rng = np.random.default_rng(6)
    items = rng.integers(0, 1 << 24, size=(512, 16), dtype=np.uint32)
    # distinct random rows: nothing to attach (verification rejects any
    # chance key collision), so the encoder declines
    assert encode_delta(items, min_delta_fraction=0.05) is None


def test_encoded_labels_bit_identical(dup_items):
    base = ClusterParams(use_pallas="interpret", block_n=128, h2d_chunks=4,
                         encoding="pack24")
    enc = ClusterParams(use_pallas="interpret", block_n=128, h2d_chunks=4,
                        encoding="delta")
    np.testing.assert_array_equal(cluster_sessions(dup_items, enc),
                                  cluster_sessions(dup_items, base))
    assert pipeline_mod.last_run_info["encoding"] == "plain"


def test_encoded_labels_bit_identical_raw_values():
    """Same parity when values exceed the 24-bit pack limit."""
    rng = np.random.default_rng(9)
    base_rows = rng.integers(0, 1 << 30, size=(128, 16), dtype=np.uint32)
    items = np.repeat(base_rows, 6, axis=0)
    mut = rng.random(items.shape) < 0.08
    items[mut] = rng.integers(0, 1 << 30, size=int(mut.sum()),
                              dtype=np.uint32)
    perm = rng.permutation(items.shape[0])
    items = items[perm]
    prm = dict(use_pallas="never", h2d_chunks=2)
    np.testing.assert_array_equal(
        cluster_sessions(items, ClusterParams(encoding="delta", **prm)),
        cluster_sessions(items, ClusterParams(encoding="pack24", **prm)))


def test_auto_policy_skips_small_inputs(dup_items):
    cluster_sessions(dup_items[:512],
                     ClusterParams(use_pallas="never", encoding="auto"))
    # small inputs skip the delta encoder; the plain adaptively-packed
    # lane ships (and is reported as such, with its per-chunk widths)
    info = pipeline_mod.last_run_info
    assert info["encoding"] == "plain"
    assert all(1 <= w <= 32 for w in info["chunk_bits"])


def test_auto_policy_engages_on_large_compressible(dup_items, monkeypatch):
    # prefilter pinned off: this test isolates the ENCODING auto policy
    # (with the size gate lowered, wire-v3's prefilter would also engage
    # and shrink the lane split below N — covered by test_prefilter.py).
    monkeypatch.setattr(pipeline_mod, "_AUTO_MIN_BYTES", 1024)
    cluster_sessions(dup_items,
                     ClusterParams(use_pallas="never", encoding="auto",
                                   prefilter="off"))
    info = pipeline_mod.last_run_info
    assert info["encoding"] == "delta"
    assert info["n_full"] + info["n_delta"] == N
    assert info["wire_mb"] <= N * 16 * 3 / 2**20


def test_resumable_encoded_matches_plain(dup_items, tmp_path):
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")
    want = cluster_sessions(dup_items, prm)
    got = cluster_sessions_resumable(dup_items, prm,
                                     checkpoint_dir=str(tmp_path / "ck"))
    np.testing.assert_array_equal(got, want)
    assert not list((tmp_path / "ck").glob("shard_*.npz"))


def test_resumable_encoded_kill_and_resume(dup_items, tmp_path, monkeypatch):
    d = str(tmp_path / "ck")
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")
    want = cluster_sessions(dup_items, prm)

    class Boom(RuntimeError):
        pass

    saved = []
    real_save = ClusterCheckpoint.save_chunk

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        saved.append(index)
        if len(saved) == 2:
            raise Boom()

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", dying_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)
    got = cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    np.testing.assert_array_equal(got, want)


def test_resumable_refuses_different_lane_split(dup_items, tmp_path,
                                                monkeypatch):
    """A resume whose encoder drew different lanes must refuse, not mix
    shards (the native/numpy encoders may legitimately group differently)."""
    d = str(tmp_path / "ck")
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")

    class Boom(RuntimeError):
        pass

    saved = []
    real_save = ClusterCheckpoint.save_chunk

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        saved.append(index)
        raise Boom()

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", dying_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)

    real_encode = pipeline_mod.encode_delta

    def other_lanes(items, **kw):
        enc = real_encode(items, **kw)
        # drop one delta row back into the full lane -> different split
        keep = np.ones(enc.n_delta, bool)
        keep[0] = False
        return _drop_delta_row(items, enc, keep)

    monkeypatch.setattr(pipeline_mod, "encode_delta", other_lanes)
    with pytest.raises(ValueError, match="different"):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)


def test_resumable_refuses_encoding_mode_change(dup_items, tmp_path,
                                                monkeypatch):
    """A delta-encoded checkpoint resumed with encoding off holds
    full-lane shards that would be misread as item-chunk shards — the
    manifest's symmetric meta comparison must refuse."""
    d = str(tmp_path / "ck")
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta")

    class Boom(RuntimeError):
        pass

    real_save = ClusterCheckpoint.save_chunk

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        raise Boom()

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", dying_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)
    plain = ClusterParams(use_pallas="never", h2d_chunks=4,
                          encoding="pack24")
    with pytest.raises(ValueError, match="different"):
        cluster_sessions_resumable(dup_items, plain, checkpoint_dir=d)


def test_unknown_encoding_rejected(dup_items):
    with pytest.raises(ValueError, match="unknown encoding"):
        cluster_sessions(dup_items[:64],
                         ClusterParams(use_pallas="never", encoding="raw"))


def _drop_delta_row(items: np.ndarray, enc: DeltaEncoding,
                    keep: np.ndarray) -> DeltaEncoding:
    """Rebuild an encoding with a subset of its delta rows (test helper)."""
    is_delta = np.unpackbits(enc.mask_bits, bitorder="little")[:enc.n]
    delta_idx = np.flatnonzero(is_delta)
    new_mask = np.zeros(enc.n, bool)
    new_mask[delta_idx[keep]] = True
    full_rank = np.cumsum(~new_mask) - 1
    rows = np.repeat(np.arange(enc.n_delta), enc.counts)
    keep_flat = keep[rows]
    # original index of each kept delta row's base
    full_idx_old = np.flatnonzero(~is_delta)
    rep_orig = full_idx_old[enc.rep_in_full]
    return DeltaEncoding(
        n=enc.n, set_size=enc.set_size,
        mask_bits=np.packbits(new_mask, bitorder="little"),
        full_rows=np.ascontiguousarray(items[~new_mask]),
        rep_in_full=full_rank[rep_orig[keep]].astype(np.int32),
        counts=enc.counts[keep],
        pos_flat=enc.pos_flat[keep_flat],
        val_flat=enc.val_flat[keep_flat],
    )


# ---------------------------------------------------------------------------
# Adaptive bit-width wire packing (this PR's wire layer).

_WIDTHS = (8, 16, 24, 32, 1, 3, 5, 6, 7, 10, 12, 17, 21, 31)


def _device_unpack(packed, n, bits, offset=0):
    import jax.numpy as jnp

    return np.asarray(pipeline_mod._unpack_bits(
        jnp.asarray(packed), n, bits, np.uint32(offset)))


@pytest.mark.parametrize("bits", _WIDTHS)
def test_bitpack_roundtrip_max_range_and_empty(bits):
    """Byte-multiple AND sub-byte widths round-trip through both the host
    oracle and the device kernel — including all-max values (every bit
    set, the mask-off edge) and the empty stream."""
    top = (1 << bits) - 1
    for n in (0, 1, 7, 8, 9, 257):
        vals = np.full(n, top, np.uint32)
        packed = pack_bits_host(vals, bits)
        assert packed.nbytes == -(-n * bits // 8)
        np.testing.assert_array_equal(unpack_bits_host(packed, n, bits),
                                      vals)
        np.testing.assert_array_equal(_device_unpack(packed, n, bits), vals)


@pytest.mark.parametrize("bits", _WIDTHS)
def test_bitpack_roundtrip_random(bits):
    rng = np.random.default_rng(bits)
    vals = rng.integers(0, 1 << bits, size=999, dtype=np.uint64).astype(
        np.uint32)
    packed = pack_bits_host(vals, bits)
    np.testing.assert_array_equal(unpack_bits_host(packed, 999, bits), vals)
    np.testing.assert_array_equal(_device_unpack(packed, 999, bits), vals)


def test_pack_chunk_adaptive_width_offset_and_device_parity():
    """A narrow value band high in the id space packs at the width of its
    RANGE (min-subtracted), and the device decode restores it exactly."""
    rng = np.random.default_rng(0)
    base = 5_000_000
    chunk = (base + rng.integers(0, 100, size=(37, 8))).astype(np.uint32)
    wire = pack_chunk(chunk)
    assert wire.bits == width_bits(int(chunk.max()) - int(chunk.min()))
    assert wire.bits <= 7 and wire.offset == int(chunk.min())
    np.testing.assert_array_equal(unpack_chunk_host(wire), chunk)
    got = _device_unpack(wire.payload, wire.n_values, wire.bits,
                         wire.offset).reshape(wire.shape)
    np.testing.assert_array_equal(got, chunk)


def test_pack_chunk_respects_pack_limit():
    """Ids at/above the limit ship raw uint32 (the historical pack24 kill
    switch), regardless of range."""
    chunk = np.array([[1 << 25, (1 << 25) + 3]], np.uint32)
    assert chunk_wire_bits(chunk, pack_limit=1 << 24) == (32, 0)
    wire = pack_chunk(chunk, pack_limit=1 << 24)
    np.testing.assert_array_equal(unpack_chunk_host(wire), chunk)
    # without the limit, the 2-wide range packs to 2 bits + offset
    assert pack_chunk(chunk, pack_limit=1 << 33).bits == 2


def test_pack_delta_meta_roundtrip(dup_items):
    """The bit-packed delta metadata lanes (rep/counts/pos/val) decode
    back to the DeltaEncoding exactly — and they are strictly smaller
    than the fixed-width lanes they replaced."""
    enc = encode_delta(dup_items, use_native=False)
    meta = pack_delta_meta(enc)  # entropy='off': the pure bit-pack form
    np.testing.assert_array_equal(
        unpack_bits_host(meta.rep.packed, enc.n_delta, meta.rep.bits),
        enc.rep_in_full.astype(np.uint32))
    np.testing.assert_array_equal(
        unpack_bits_host(meta.counts.packed, enc.n_delta, meta.counts.bits),
        enc.counts.astype(np.uint32))
    np.testing.assert_array_equal(
        unpack_bits_host(meta.pos.packed, len(enc.pos_flat), meta.pos.bits),
        enc.pos_flat.astype(np.uint32))
    np.testing.assert_array_equal(unpack_chunk_host(meta.val), enc.val_flat)
    fixed = (enc.rep_in_full.nbytes + enc.counts.nbytes + enc.pos_flat.nbytes
             + enc.val_flat.nbytes)
    assert meta.nbytes < fixed


if HAVE_HYPOTHESIS:

    @settings(max_examples=80, deadline=None)
    @given(st.data())
    def test_bitpack_roundtrip_property(data):
        """Hypothesis sweep over width x length x values (including the
        degenerate empty chunk and max-range draws)."""
        bits = data.draw(st.sampled_from(_WIDTHS), label="bits")
        n = data.draw(st.integers(min_value=0, max_value=130), label="n")
        top = (1 << bits) - 1
        vals = np.asarray(
            data.draw(st.lists(st.integers(0, top), min_size=n, max_size=n),
                      label="vals"), dtype=np.uint32).reshape(n)
        packed = pack_bits_host(vals, bits)
        assert packed.nbytes == -(-n * bits // 8)
        np.testing.assert_array_equal(unpack_bits_host(packed, n, bits),
                                      vals)
        np.testing.assert_array_equal(_device_unpack(packed, n, bits), vals)

    @settings(max_examples=40, deadline=None)
    @given(st.data())
    def test_pack_chunk_roundtrip_property(data):
        """pack_chunk picks a legal width for ANY uint32 chunk and
        round-trips bit-exactly through host and device decoders."""
        rows = data.draw(st.integers(0, 24), label="rows")
        cols = data.draw(st.integers(1, 9), label="cols")
        hi = data.draw(st.sampled_from(
            [1 << 4, 1 << 12, 1 << 24, (1 << 32) - 1]), label="hi")
        lo = data.draw(st.integers(0, hi), label="lo")
        rng = np.random.default_rng(data.draw(st.integers(0, 2**16)))
        chunk = rng.integers(lo, hi + 1, size=(rows, cols),
                             dtype=np.uint64).astype(np.uint32)
        wire = pack_chunk(chunk)
        assert 1 <= wire.bits <= 32
        np.testing.assert_array_equal(unpack_chunk_host(wire), chunk)
        got = _device_unpack(wire.payload, wire.n_values, wire.bits,
                             wire.offset).reshape(wire.shape)
        np.testing.assert_array_equal(got, chunk)

else:  # pragma: no cover - environment without hypothesis

    @pytest.mark.skip(reason="property tests need hypothesis "
                             "(pip install -e .[test])")
    def test_bitpack_roundtrip_property():
        pass

    @pytest.mark.skip(reason="property tests need hypothesis "
                             "(pip install -e .[test])")
    def test_pack_chunk_roundtrip_property():
        pass


# ---------------------------------------------------------------------------
# Wire quantization (b-bit-minwise universe reduction).

def test_quantize_ids_deterministic_and_bounded():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 1 << 32, size=(64, 8), dtype=np.uint64).astype(
        np.uint32)
    q = quantize_ids(x, 10)
    assert int(q.max()) < 1 << 10
    np.testing.assert_array_equal(q, quantize_ids(x, 10))  # deterministic
    # equal ids collide identically: exact-duplicate rows stay duplicates
    np.testing.assert_array_equal(quantize_ids(x[0], 10),
                                  quantize_ids(x[0].copy(), 10))


def test_quant_auto_policy(dup_items, monkeypatch):
    prm = ClusterParams(use_pallas="never")
    # small input: auto stays off
    assert pipeline_mod._quant_bits(dup_items, prm) == 0
    # large input (threshold lowered): auto engages at _AUTO_QUANT_BITS
    monkeypatch.setattr(pipeline_mod, "_AUTO_MIN_BYTES", 1024)
    assert pipeline_mod._quant_bits(dup_items, prm) \
        == pipeline_mod._AUTO_QUANT_BITS
    # explicit off wins over size
    off = ClusterParams(use_pallas="never", wire_quant_bits=-1)
    assert pipeline_mod._quant_bits(dup_items, off) == 0
    # no gain when ids already fit the target universe
    assert pipeline_mod._quant_bits((dup_items & 511), prm) == 0


def test_quantized_labels_parity_across_encodings(dup_items):
    """Forced quantization must leave delta and plain paths bit-identical
    to each other (both cluster quantize_ids(items)) and equal to
    clustering the pre-quantized items directly."""
    prm = dict(use_pallas="never", h2d_chunks=3, wire_quant_bits=12)
    delta = cluster_sessions(dup_items, ClusterParams(encoding="delta",
                                                      **prm))
    assert pipeline_mod.last_run_info["wire_quant_bits"] == 12
    assert max(pipeline_mod.last_run_info["chunk_bits"]) <= 12
    plain = cluster_sessions(dup_items, ClusterParams(encoding="pack24",
                                                      **prm))
    np.testing.assert_array_equal(delta, plain)
    oracle = cluster_sessions(quantize_ids(dup_items, 12),
                              ClusterParams(use_pallas="never",
                                            h2d_chunks=3,
                                            wire_quant_bits=-1))
    np.testing.assert_array_equal(delta, oracle)


def test_quantized_resumable_matches_and_refuses_policy_change(dup_items,
                                                               tmp_path):
    prm = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta",
                        wire_quant_bits=11)
    want = cluster_sessions(dup_items, prm)
    d = str(tmp_path / "ck")
    got = cluster_sessions_resumable(dup_items, prm, checkpoint_dir=d,
                                     cleanup=False)
    np.testing.assert_array_equal(got, want)
    # same directory, different quantization policy -> refuse
    other = ClusterParams(use_pallas="never", h2d_chunks=4, encoding="delta",
                          wire_quant_bits=9)
    with pytest.raises(ValueError, match="different"):
        cluster_sessions_resumable(dup_items, other, checkpoint_dir=d)


def test_wire_payloads_matches_pipeline_decision(dup_items, monkeypatch):
    """bench's transfer probe ships wire_payloads — its byte count and
    encoding decision must equal what the timed pipeline reports."""
    monkeypatch.setattr(pipeline_mod, "_AUTO_MIN_BYTES", 1024)
    prm = ClusterParams(use_pallas="never", h2d_chunks=2)
    payloads, winfo = pipeline_mod.wire_payloads(dup_items, prm)
    cluster_sessions(dup_items, prm)
    info = pipeline_mod.last_run_info
    assert winfo["encoding"] == info["encoding"] == "delta"
    assert winfo["wire_quant_bits"] == info["wire_quant_bits"]
    assert abs(winfo["wire_mb"] - info["wire_mb"]) < 0.02
    assert sum(p.nbytes for p in payloads) == pytest.approx(
        winfo["wire_mb"] * 2**20, abs=2**14)
