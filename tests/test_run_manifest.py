"""Run-to-completion orchestration (resilience/runner.py + cli all):
one RQ failing no longer aborts the rest, missing steps are recorded
instead of silently dropped, and the exit code reflects partial failure
— with every step's status/attempts/traceback in run_manifest.json.
"""

from __future__ import annotations

import importlib
import json
import os
import sys
import types

import pytest

from tse1m_tpu.resilience import RetryPolicy, StepRunner


# -- StepRunner unit ----------------------------------------------------------

def _read(path):
    with open(path) as f:
        return json.load(f)


def test_step_runner_records_ok_and_failed(tmp_path):
    man = str(tmp_path / "m.json")
    r = StepRunner(man)
    r.run("good", lambda: 42)
    r.run("bad", lambda: 1 / 0)
    r.record_missing("ghost", "module not importable")
    payload = _read(man)
    assert payload["ok"] is False
    assert payload["summary"] == {"ok": 1, "failed": 1, "missing": 1}
    by_name = {s["name"]: s for s in payload["steps"]}
    assert by_name["good"]["status"] == "ok"
    assert by_name["good"]["attempts"] == 1
    assert by_name["bad"]["status"] == "failed"
    assert "ZeroDivisionError" in by_name["bad"]["error"]
    assert "1 / 0" in by_name["bad"]["traceback"]
    assert by_name["ghost"]["status"] == "missing"
    assert r.exit_code() == 1


def test_step_runner_embeds_stage_telemetry(tmp_path):
    """A step whose body records pipeline stage timings (observability
    plane) gets them embedded in its manifest record; steps that record
    nothing stay stage-free — including a step AFTER a recording one (the
    runner clears the handoff slot per step)."""
    from tse1m_tpu.observability import StageRecorder, record_last_stages

    def staged_step():
        rec = StageRecorder()
        rec.add("encode", 0.5, 1 << 20)
        rec.add("h2d", 2.0, 1 << 20)
        rec.add("compute", 1.75)
        rec.set_total(2.5)
        record_last_stages(rec.as_dict())
        return 1

    man = str(tmp_path / "m.json")
    r = StepRunner(man)
    r.run("cluster", staged_step)
    r.run("plain", lambda: 2)
    by_name = {s["name"]: s for s in _read(man)["steps"]}
    stages = by_name["cluster"]["stages"]
    assert stages["stage_h2d_s"] == 2.0
    assert stages["stage_encode_mb"] == 1.0
    # sum(stages)=4.25, wall=2.5 -> 1.75 s hidden, all of it h2d time
    assert stages["h2d_overlap_fraction"] == 0.875
    assert by_name["plain"]["stages"] is None


def test_step_runner_all_ok_exit_zero(tmp_path):
    man = str(tmp_path / "m.json")
    r = StepRunner(man)
    r.run("a", lambda: None)
    r.run("b", lambda: None)
    assert r.exit_code() == 0
    assert _read(man)["ok"] is True


def test_step_runner_manifest_written_after_every_step(tmp_path):
    """A kill mid-run must leave an accurate partial record."""
    man = str(tmp_path / "m.json")
    r = StepRunner(man)
    r.run("first", lambda: None)
    midway = _read(man)
    assert [s["name"] for s in midway["steps"]] == ["first"]
    r.run("second", lambda: None)
    assert [s["name"] for s in _read(man)["steps"]] == ["first", "second"]


def test_step_runner_retries_when_policy_allows(tmp_path):
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")

    r = StepRunner(str(tmp_path / "m.json"),
                   policy=RetryPolicy(max_attempts=5, base_delay=0))
    rec = r.run("flaky", flaky)
    assert rec.status == "ok"
    assert rec.attempts == 3
    assert r.exit_code() == 0


def test_step_runner_empty_run_is_a_failure(tmp_path):
    assert StepRunner(str(tmp_path / "m.json")).exit_code() == 1


# -- cli all ------------------------------------------------------------------

RQ_SPECS = {
    "tse1m_tpu.analysis.rq1": "run_rq1",
    "tse1m_tpu.analysis.rq2_changepoints": "run_rq2_changepoints",
    "tse1m_tpu.analysis.rq2_trends": "run_rq2_trends",
    "tse1m_tpu.analysis.rq3": "run_rq3",
    "tse1m_tpu.analysis.rq4a": "run_rq4a",
    "tse1m_tpu.analysis.rq4b": "run_rq4b",
}


@pytest.fixture
def stub_rqs(monkeypatch):
    """Replace every RQ module with a stub that drops a marker file; rq3
    raises (permanent fault), rq4a is unimportable (missing module)."""
    real_import = importlib.import_module

    def make_module(mod_name, fn_name):
        mod = types.ModuleType(mod_name)

        def run(cfg, _name=mod_name):
            short = _name.rsplit(".", 1)[1]
            if short == "rq3":
                raise RuntimeError("permanent rq fault")
            os.makedirs(cfg.result_dir, exist_ok=True)
            with open(os.path.join(cfg.result_dir, short + ".ran"), "w"):
                pass

        setattr(mod, fn_name, run)
        return mod

    def fake_import(name, *a, **kw):
        if name == "tse1m_tpu.analysis.rq4a":
            raise ModuleNotFoundError(f"No module named {name!r}", name=name)
        if name in RQ_SPECS:
            return make_module(name, RQ_SPECS[name])
        return real_import(name, *a, **kw)

    monkeypatch.setattr(importlib, "import_module", fake_import)
    return fake_import


def test_cli_all_runs_survivors_and_reports_failures(tmp_path, stub_rqs,
                                                     monkeypatch):
    from tse1m_tpu import cli

    out = str(tmp_path / "results")
    monkeypatch.setenv("TSE1M_RESULT_DIR", out)
    rc = cli.main(["all"])
    assert rc == 1  # rq3 failed, rq4a missing
    # survivors all completed
    for short in ("rq1", "rq2_changepoints", "rq2_trends", "rq4b"):
        assert os.path.exists(os.path.join(out, short + ".ran")), short
    payload = _read(os.path.join(out, "run_manifest.json"))
    by_name = {s["name"]: s for s in payload["steps"]}
    assert set(by_name) == {"graftlint", "graftspec", "rq1", "rq2a",
                            "rq2b", "rq3", "rq4a", "rq4b"}
    # the correctness step records its structured summary per run
    lint = by_name["graftlint"]
    assert lint["status"] == "ok"
    assert lint["result"]["new_findings"] == 0
    assert lint["result"]["runtime"]["sanitizer_available"] is True
    # graftlint v2: the manifest records the whole-program run's shape —
    # per-rule finding totals (proof the rules ran) plus the digest
    # cache's hit rate and the graph/wall numbers.
    assert "cache_hit_rate" in lint["result"]
    assert lint["result"]["graph_functions"] > 100
    assert lint["result"]["wall_s"] > 0
    assert "by_rule_total" in lint["result"]
    # graftspec: every committed spec model-checked clean, every mutant
    # caught with a replayed counterexample — recorded per run.
    spec = by_name["graftspec"]
    assert spec["status"] == "ok"
    checked = {s["spec"]: s for s in spec["result"]["specs"]}
    assert set(checked) == {"lease", "ingest_ack", "replica"}
    assert all(s["ok"] and s["complete"] for s in checked.values())
    assert all(m["caught"] and m["replayed"]
               for m in spec["result"]["mutants"].values())
    assert by_name["rq3"]["status"] == "failed"
    assert "permanent rq fault" in by_name["rq3"]["error"]
    assert "permanent rq fault" in by_name["rq3"]["traceback"]
    assert by_name["rq4a"]["status"] == "missing"
    assert all(by_name[k]["status"] == "ok"
               for k in ("rq1", "rq2a", "rq2b", "rq4b"))


def test_cli_single_rq_failure_is_nonzero_and_recorded(tmp_path, stub_rqs,
                                                       monkeypatch):
    from tse1m_tpu import cli

    out = str(tmp_path / "results")
    monkeypatch.setenv("TSE1M_RESULT_DIR", out)
    assert cli.main(["rq3"]) == 1
    payload = _read(os.path.join(out, "run_manifest.json"))
    assert payload["steps"][0]["status"] == "failed"
    assert cli.main(["rq1"]) == 0


def test_cli_missing_single_rq_exits_nonzero(tmp_path, stub_rqs, monkeypatch):
    from tse1m_tpu import cli

    monkeypatch.setenv("TSE1M_RESULT_DIR", str(tmp_path / "r"))
    assert cli.main(["rq4a"]) == 1
