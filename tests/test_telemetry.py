"""Telemetry plane: span tracing, the metrics registry + exporters, and
the crash-time flight recorder (tse1m_tpu/observability).

Covers the span model (ids, nesting, propagation contexts), the bounded
span ring under concurrent writers with the lockset detector armed, the
typed metrics registry and its Prometheus/flat/snapshot exporters, the
StageRecorder and degradation-counter absorption into the registry, the
pod-manifest metrics/trace merge, and the flight recorder's dump format.
The cross-PROCESS propagation proof (one trace id in both pod manifest
fragments) runs as a slow 2-process integration test; the serve-plane
wire propagation (client -> daemon -> store append) is asserted against
a real daemon in-process."""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

from tse1m_tpu.observability import metrics as obs_metrics
from tse1m_tpu.observability import tracing
from tse1m_tpu.observability.export import (flat_metrics, metrics_snapshot,
                                            prometheus_text)
from tse1m_tpu.observability.flight import (dump_flight, get_flight_dir,
                                            set_flight_dir)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Each test starts from an empty ring/registry and no pinned trace
    or flight dir — telemetry state is process-global by design."""
    tracing.set_tracing(True)
    tracing.adopt_trace(None)
    tracing.clear_spans()
    obs_metrics.reset_metrics()
    set_flight_dir(None)
    yield
    tracing.set_tracing(True)
    tracing.adopt_trace(None)
    tracing.clear_spans()
    obs_metrics.reset_metrics()
    set_flight_dir(None)


# -- spans --------------------------------------------------------------------

def test_span_records_and_nests():
    with tracing.span("outer", kind="test") as outer:
        with tracing.span("inner") as inner:
            assert inner.trace == outer.trace
            assert inner.parent == outer.span_id
    recs = tracing.recent_spans()
    assert [r["name"] for r in recs] == ["inner", "outer"]
    inner_rec, outer_rec = recs
    assert inner_rec["trace"] == outer_rec["trace"]
    assert inner_rec["parent"] == outer_rec["span"]
    assert outer_rec["parent"] == ""
    assert outer_rec["ok"] is True
    assert outer_rec["tags"] == {"kind": "test"}
    assert outer_rec["dur_s"] >= inner_rec["dur_s"]


def test_span_failure_marks_record_not_ok():
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("x")
    assert tracing.recent_spans()[-1]["ok"] is False


def test_adopted_trace_roots_all_spans():
    tid = tracing.new_trace_id()
    tracing.adopt_trace(tid)
    with tracing.span("a"):
        pass
    with tracing.span("b"):
        pass
    assert {r["trace"] for r in tracing.recent_spans()} == {tid}
    assert tracing.pinned_trace() == tid


def test_continue_trace_joins_remote_context():
    with tracing.span("client") as sp:
        ctx = tracing.current_trace()
        assert ctx == {"t": sp.trace, "s": sp.span_id}
    with tracing.continue_trace(ctx):
        with tracing.span("server"):
            pass
    server = tracing.recent_spans()[-1]
    assert server["trace"] == ctx["t"]
    assert server["parent"] == ctx["s"]
    # falsy context: no-op, spans root normally instead of crashing
    with tracing.continue_trace(None):
        with tracing.span("solo"):
            pass
    assert tracing.recent_spans()[-1]["parent"] == ""


def test_set_tracing_off_records_nothing():
    tracing.set_tracing(False)
    with tracing.span("ghost") as sp:
        sp.set_tag("k", 1)  # the no-op span absorbs the full API
    assert tracing.spans_recorded() == 0


def test_ring_bounded_keeps_most_recent():
    ring = tracing.SpanRing(capacity=4)
    for i in range(10):
        ring.append({"name": f"s{i}"})
    assert ring.total() == 10
    assert [r["name"] for r in ring.recent()] == ["s6", "s7", "s8", "s9"]
    assert [r["name"] for r in ring.recent(2)] == ["s8", "s9"]


def test_ring_lockset_clean_under_concurrent_writers():
    """The ring is telemetry's hottest shared object: hammer it from
    worker threads under the Eraser lockset detector — its traced lock
    must cover every buffer access."""
    from tse1m_tpu.trace import traced

    ring = tracing.SpanRing(capacity=64)
    with traced() as tracer:
        def writer(k: int) -> None:
            for i in range(200):
                ring.append({"name": f"w{k}.{i}"})

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert ring.total() == 800
        assert len(ring.recent()) == 64
    assert not tracer.lockset.races


# -- metrics registry + exporters ---------------------------------------------

def test_counter_gauge_histogram_basics():
    c = obs_metrics.counter("req_total", op="query")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    g = obs_metrics.gauge("depth")
    g.set(5)
    g.set_max(3)   # high-water: lower values don't regress it
    assert g.value == 5
    g.set_max(9)
    assert g.value == 9
    h = obs_metrics.histogram("lat_s")
    h.observe(0.010)
    with h.time():
        pass
    snap = h.snapshot()
    assert snap["count"] == 2
    assert snap["p99_ms"] >= 0


def test_registry_labels_are_distinct_series_and_kinds_checked():
    obs_metrics.counter("hits", site="a").inc()
    obs_metrics.counter("hits", site="b").inc(4)
    names = {(m.name, tuple(sorted(m.labels.items()))): m
             for m in obs_metrics.get_registry().collect()}
    assert names[("hits", (("site", "a"),))].value == 1
    assert names[("hits", (("site", "b"),))].value == 4
    with pytest.raises(TypeError):
        obs_metrics.gauge("hits", site="a")  # kind mismatch on one name


def test_prometheus_text_format():
    obs_metrics.counter("req_total", op="query").inc(3)
    obs_metrics.gauge("depth").set(7)
    obs_metrics.histogram("lat_s").observe(0.011)
    text = prometheus_text()
    assert "# TYPE req_total counter" in text
    assert 'req_total{op="query"} 3' in text
    assert "# TYPE depth gauge" in text
    assert "depth 7" in text
    assert "# TYPE lat_s histogram" in text
    assert 'lat_s_bucket{le="+Inf"} 1' in text
    assert "lat_s_count 1" in text


def test_flat_metrics_and_snapshot_shapes():
    obs_metrics.counter("x_total").inc(3)
    obs_metrics.gauge("depth").set(7)
    obs_metrics.histogram("lat_s").observe(0.011)
    flat = flat_metrics()
    assert flat["metrics_x_total"] == 3
    assert flat["metrics_depth"] == 7.0
    assert flat["metrics_lat_s_count"] == 1
    assert flat["metrics_lat_s_p99_ms"] > 0
    snap = metrics_snapshot()
    assert json.loads(json.dumps(snap)) == snap  # JSON-safe verbatim
    assert [c["name"] for c in snap["counters"]] == ["x_total"]
    assert snap["histograms"][0]["count"] == 1
    assert snap["histograms"][0]["buckets"]


def test_stage_recorder_feeds_registry_and_as_dict_unchanged():
    from tse1m_tpu.observability import StageRecorder

    rec = StageRecorder()
    rec.add("encode", 0.25)
    rec.add("encode", 0.05)
    rec.add("h2d", 0.10, nbytes=1 << 20)
    d = rec.as_dict()
    assert d["stage_encode_s"] == 0.3   # legacy output shape intact
    assert d["stage_h2d_s"] == 0.1
    assert d["stage_h2d_mb"] == 1.0
    snap = {(h["name"], tuple(sorted(h["labels"].items()))): h
            for h in metrics_snapshot()["histograms"]}
    assert snap[("stage_seconds", (("stage", "encode"),))]["count"] == 2
    assert snap[("stage_seconds", (("stage", "h2d"),))]["count"] == 1


def test_record_degradation_counts_in_registry():
    from tse1m_tpu.observability import (pop_degradation_events,
                                         record_degradation)

    record_degradation("stall_retry", site="pipeline.h2d")
    record_degradation("stall_retry", site="pipeline.h2d")
    record_degradation("chunk_halving", site="pipeline")
    pop_degradation_events()
    flat = flat_metrics()
    assert flat["metrics_degradations_total"] == 3


def test_merge_metric_snapshots_and_trace_ids(tmp_path):
    from tse1m_tpu.observability.merge import (fragment_manifest_path,
                                               merge_run_manifests)

    def frag(pid: int, hits: int, depth: float) -> None:
        payload = {
            "ok": True, "summary": {"ok": 1}, "steps": [],
            "degradation_counts": {}, "trace_id": "cafe" * 4,
            "metrics": {
                "counters": [{"name": "hits", "labels": {},
                              "value": hits}],
                "gauges": [{"name": "depth", "labels": {},
                            "value": depth}],
                "histograms": [{"name": "lat_s", "labels": {},
                                "count": 2, "sum": 0.5, "p50_ms": 1.0,
                                "p99_ms": float(pid + 1), "max_ms": 9.0,
                                "buckets": []}],
            },
        }
        with open(fragment_manifest_path(str(tmp_path), pid), "w") as f:
            json.dump(payload, f)

    frag(0, hits=2, depth=3.0)
    frag(1, hits=5, depth=1.0)
    merged = merge_run_manifests(str(tmp_path), 2)
    assert merged["trace_id"] == "cafe" * 4  # both fragments agree
    m = merged["metrics"]
    assert m["counters"][0]["value"] == 7        # counters sum
    assert m["gauges"][0]["value"] == 3.0        # gauges keep pod max
    h = m["histograms"][0]
    assert h["count"] == 4 and h["sum"] == 1.0   # histogram counts sum
    assert h["p99_ms"] == 2.0                    # worst p99 survives


# -- flight recorder ----------------------------------------------------------

def test_dump_flight_noop_without_dir():
    assert get_flight_dir() is None
    assert dump_flight("unit_test") is None


def test_dump_flight_format_and_numbering(tmp_path):
    set_flight_dir(str(tmp_path))
    tracing.adopt_trace("feed" * 4)
    obs_metrics.counter("boom_total").inc()
    with tracing.span("work", step="s1"):
        pass
    p0 = dump_flight("unit_test", site="seat.x", extra={"k": 1})
    p1 = dump_flight("unit_test", site="seat.x")
    assert os.path.basename(p0) == "flight_000.json"
    assert os.path.basename(p1) == "flight_001.json"
    flight = json.load(open(p0))
    assert flight["reason"] == "unit_test"
    assert flight["site"] == "seat.x"
    assert flight["trace_id"] == "feed" * 4
    assert flight["extra"] == {"k": 1}
    # the terminal span is the dump's own marker, naming the seat; the
    # preceding span is the work that was in flight
    assert flight["spans"][-1]["name"] == "flight.unit_test"
    assert flight["spans"][-1]["tags"]["site"] == "seat.x"
    assert flight["spans"][-2]["name"] == "work"
    assert {c["name"] for c in flight["metrics"]["counters"]} \
        >= {"boom_total"}


def test_env_var_seeds_flight_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("TSE1M_FLIGHT_DIR", str(tmp_path))
    assert get_flight_dir() == str(tmp_path)
    assert dump_flight("env_seeded") is not None
    set_flight_dir(str(tmp_path / "explicit"))  # explicit call wins
    assert get_flight_dir() == str(tmp_path / "explicit")


# -- serve-plane propagation: client -> daemon -> store append ----------------

def test_serve_request_yields_one_correlated_trace(tmp_path):
    """One ingest request over the real TCP transport produces one
    trace spanning the client span, the server dispatch, the ingest
    thread's batch span, and the store append — the acceptance
    criterion's correlated-trace contract — and the live ``metrics`` /
    ``trace`` verbs serve the telemetry back."""
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.serve import ServeClient, ServeDaemon, ServeServer

    items, _ = synth_session_sets(64, set_size=32, seed=3)
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
    daemon = ServeDaemon(str(tmp_path / "store"), params=params).start()
    server = ServeServer(daemon)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        with ServeClient(port=server.port) as c:
            r = c.ingest(items)
            assert r["ok"]
            c.quiesce(timeout_s=60)
            m = c.metrics()
            t = c.trace()
        assert m["ok"] and "# TYPE" in m["prometheus"]
        # content-addressed store: rows reflect UNIQUE contents
        assert int(m["metrics"]["metrics_serve_store_rows"]) \
            == daemon.store.n_rows > 0
        assert t["ok"] and t["spans_recorded"] > 0
        by_name = {}
        for rec in t["spans"]:
            by_name.setdefault(rec["name"], rec)
        chain = ["client.ingest", "serve.ingest", "serve.ingest.batch",
                 "store.append"]
        missing = [n for n in chain if n not in by_name]
        assert not missing, (missing, sorted(by_name))
        # one trace id across the whole chain, parents linking through
        tid = by_name["client.ingest"]["trace"]
        assert all(by_name[n]["trace"] == tid for n in chain), by_name
        assert by_name["serve.ingest"]["parent"] == \
            by_name["client.ingest"]["span"]
        assert by_name["store.append"]["parent"] == \
            by_name["serve.ingest.batch"]["span"]
    finally:
        daemon.stop()
        server.server_close()


def test_serve_status_surfaces_backlog_history(tmp_path):
    """Satellite: `--status` used to report queue depth point-in-time
    only; the registry-backed high-water mark and rejection counter
    survive the drain."""
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.serve import ServeDaemon

    items, _ = synth_session_sets(32, set_size=32, seed=5)
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
    # Submit BEFORE starting the drain loop so the backlog depth is
    # deterministic: three queued batches = high-water of 2 ahead.
    daemon = ServeDaemon(str(tmp_path / "store"), params=params)
    try:
        for lo in (0, 11, 22):
            daemon.submit(items[lo:lo + 11])
        daemon.start()
        daemon.quiesce(timeout=60)
        status = daemon.status()
        assert status["queue_depth"] == 0          # drained by quiesce
        assert status["queue_depth_hwm"] == 2      # ...but history kept
        assert status["ingest_rejected_total"] == 0
    finally:
        daemon.stop()


# -- cross-process pod propagation -------------------------------------------

@pytest.mark.slow
def test_pod_run_shares_one_trace_across_fragments(tmp_path):
    """A clean 2-process pod run negotiates one nonce, pins it as the
    trace id in BOTH worker processes, and each manifest fragment (and
    the merged manifest) carries that one id."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from pod_harness import spawn_pod

    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    rdir = os.path.join(tmp, "results")
    res = spawn_pod(tmp, store, rdir, n=400, seed=13,
                    expect_finish=(0, 1))
    assert res[0]["rc"] == 0, res[0]["err"][-4000:]
    assert res[1]["rc"] == 0, res[1]["err"][-4000:]
    frags = [json.load(open(os.path.join(
        rdir, f"run_manifest.p{pid:03d}.json"))) for pid in (0, 1)]
    tids = {f["trace_id"] for f in frags}
    assert len(tids) == 1 and None not in tids, tids
    assert all(f["spans_recorded"] > 0 for f in frags), frags
    merged = json.load(open(os.path.join(rdir, "run_manifest.json")))
    assert merged["trace_id"] == tids.pop()
    assert merged["metrics"]["histograms"], merged["metrics"]
