"""Shared harness for the serving-plane chaos tests and the CI
fault-matrix ``serve-kill`` seat: spawn the daemon subprocess
(chaos_drivers ``serve``), wait for its port file, and run the
SIGKILL-mid-ingest round asserting the durability contract — every
ACKNOWLEDGED batch survives the kill; the in-flight unacked batch
recomputes on re-ingest; post-quiesce labels equal a cold batch run
elementwise."""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # the fault-matrix driver runs file-direct
    sys.path.insert(0, REPO)

# The driver's hash policy (chaos_drivers.run_serve) — the parent's cold
# oracle must match it for elementwise parity.
SERVE_PARAMS = dict(n_hashes=32, n_bands=4, use_pallas="never")


def spawn_serve(store_dir: str, port_file: str,
                plan_path: str | None = None,
                state_every: int = 2,
                timeout_s: float = 180.0) -> tuple:
    """Start the daemon subprocess; returns (proc, port) once the port
    file lands (the daemon is accepting)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("TSE1M_FAULT_PLAN", None)
    if plan_path:
        env["TSE1M_FAULT_PLAN"] = plan_path
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "chaos_drivers.py"),
         "serve", "--store-dir", store_dir, "--port-file", port_file,
         "--state-every", str(state_every)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(port_file):
            with open(port_file, encoding="utf-8") as f:
                txt = f.read().strip()
            if txt:
                return proc, int(txt)
        if proc.poll() is not None:
            out, err = proc.communicate(timeout=10)
            raise AssertionError(
                f"serve driver died before binding (rc={proc.returncode})"
                f"\n{err[-3000:]}")
        time.sleep(0.05)
    proc.kill()
    raise AssertionError("serve driver never wrote its port file")


def serve_kill_round(tmp: str, n: int = 900, batch: int = 100,
                     kill_batch: int = 3, seed: int = 13) -> dict:
    """The SIGKILL-mid-ingest game-day, shared by pytest and the CI
    fault matrix.  Returns summary counters for the matrix report."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.serve import ServeClient

    items, _ = synth_session_sets(n, set_size=64, seed=seed)
    cold = cluster_sessions(items, ClusterParams(**SERVE_PARAMS))
    store = os.path.join(tmp, "serve_store")
    port_file = os.path.join(tmp, "port")
    plan_path = os.path.join(tmp, "plan.json")
    with open(plan_path, "w", encoding="utf-8") as f:
        json.dump({"rules": [{"site": "serve.ingest.commit",
                              "kind": "kill",
                              "after_calls": kill_batch}]}, f)
    # state_every=1: the state commit trails each acked batch, so the
    # deterministic kill (before the NEXT batch's append) leaves state
    # covering exactly the acked session sequence — recovery reproduces
    # the row space and the final parity check can be ELEMENTWISE.  The
    # state-lagging recovery shape (absorb acked rows from the store)
    # is covered in-process by tests/test_serve.py.
    proc, port = spawn_serve(store, port_file, plan_path=plan_path,
                             state_every=1)
    acked_rows = 0
    killed_at = None
    try:
        with ServeClient(port=port) as c:
            for i, lo in enumerate(range(0, n, batch)):
                try:
                    r = c.ingest(items[lo:lo + batch], timeout_s=120)
                    assert r["ok"], r
                    acked_rows = lo + batch
                except Exception:  # noqa: BLE001 — the kill severs the socket; any transport error is the signal
                    killed_at = i
                    break
    finally:
        rc = proc.wait(timeout=120)
    assert killed_at == kill_batch, \
        f"kill fired at batch {killed_at}, planned {kill_batch} (rc={rc})"
    assert rc == -signal.SIGKILL, f"driver rc={rc}, wanted SIGKILL"
    assert acked_rows == kill_batch * batch
    # Flight recorder: the kill seat's last words.  The fault plane dumps
    # the ring + metrics to the daemon's flight dir (its store dir)
    # BEFORE SIGKILLing itself, and the dump's terminal span names the
    # seat that fired — the post-mortem contract.
    flights = sorted(glob.glob(os.path.join(store, "flight_*.json")))
    assert flights, "kill seat left no flight recorder dump"
    with open(flights[-1], encoding="utf-8") as f:
        flight = json.load(f)
    assert flight["reason"] == "fault.kill", flight["reason"]
    assert flight["site"] == "serve.ingest.commit", flight["site"]
    last = flight["spans"][-1]
    assert last["name"] == "flight.fault.kill", last
    assert last["tags"].get("site") == "serve.ingest.commit", last
    assert flight["metrics"]["counters"], "flight dump lost the registry"
    # Restart on the same store, NO fault plan: every acknowledged row
    # must still be served (known=True) — zero lost acked rows.
    os.remove(port_file)
    proc2, port2 = spawn_serve(store, port_file)
    try:
        with ServeClient(port=port2) as c:
            resp = c.query(items[:acked_rows])
            lost = int((~resp["known"]).sum())
            assert lost == 0, f"{lost} acknowledged rows lost to SIGKILL"
            # Re-ingest from the first unacknowledged batch on (the
            # killed batch recomputes; acked rows dedupe in the store)
            # and assert full elementwise parity with the cold run.
            for lo in range(acked_rows, n, batch):
                c.ingest(items[lo:lo + batch], timeout_s=120)
            c.quiesce(timeout_s=120)
            final = c.query(items)
            assert bool(final["known"].all())
            assert np.array_equal(final["labels"], cold), \
                "post-recovery serving labels diverged from cold batch"
            status = c.status()
            c.shutdown()
    finally:
        rc2 = proc2.wait(timeout=120)
    assert rc2 == 0, rc2
    return {"acked_before_kill": acked_rows, "lost_acked": 0,
            "rows": int(status["rows"]),
            "generation": int(status["generation"])}


__all__ = ["SERVE_PARAMS", "serve_kill_round", "spawn_serve"]
