"""Shared harness for the serving-plane chaos tests and the CI
fault-matrix ``serve-kill`` seat: spawn the daemon subprocess
(chaos_drivers ``serve``), wait for its port file, and run the
SIGKILL-mid-ingest round asserting the durability contract — every
ACKNOWLEDGED batch survives the kill; the in-flight unacked batch
recomputes on re-ingest; post-quiesce labels equal a cold batch run
elementwise."""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # the fault-matrix driver runs file-direct
    sys.path.insert(0, REPO)

# The driver's hash policy (chaos_drivers.run_serve) — the parent's cold
# oracle must match it for elementwise parity.
SERVE_PARAMS = dict(n_hashes=32, n_bands=4, use_pallas="never")


def spawn_serve(store_dir: str, port_file: str,
                plan_path: str | None = None,
                state_every: int = 2,
                timeout_s: float = 180.0) -> tuple:
    """Start the daemon subprocess; returns (proc, port) once the port
    file lands (the daemon is accepting)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("TSE1M_FAULT_PLAN", None)
    if plan_path:
        env["TSE1M_FAULT_PLAN"] = plan_path
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "chaos_drivers.py"),
         "serve", "--store-dir", store_dir, "--port-file", port_file,
         "--state-every", str(state_every)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(port_file):
            with open(port_file, encoding="utf-8") as f:
                txt = f.read().strip()
            if txt:
                return proc, int(txt)
        if proc.poll() is not None:
            out, err = proc.communicate(timeout=10)
            raise AssertionError(
                f"serve driver died before binding (rc={proc.returncode})"
                f"\n{err[-3000:]}")
        time.sleep(0.05)
    proc.kill()
    raise AssertionError("serve driver never wrote its port file")


def serve_kill_round(tmp: str, n: int = 900, batch: int = 100,
                     kill_batch: int = 3, seed: int = 13) -> dict:
    """The SIGKILL-mid-ingest game-day, shared by pytest and the CI
    fault matrix.  Returns summary counters for the matrix report."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.serve import ServeClient

    items, _ = synth_session_sets(n, set_size=64, seed=seed)
    cold = cluster_sessions(items, ClusterParams(**SERVE_PARAMS))
    store = os.path.join(tmp, "serve_store")
    port_file = os.path.join(tmp, "port")
    plan_path = os.path.join(tmp, "plan.json")
    with open(plan_path, "w", encoding="utf-8") as f:
        json.dump({"rules": [{"site": "serve.ingest.commit",
                              "kind": "kill",
                              "after_calls": kill_batch}]}, f)
    # state_every=1: the state commit trails each acked batch, so the
    # deterministic kill (before the NEXT batch's append) leaves state
    # covering exactly the acked session sequence — recovery reproduces
    # the row space and the final parity check can be ELEMENTWISE.  The
    # state-lagging recovery shape (absorb acked rows from the store)
    # is covered in-process by tests/test_serve.py.
    proc, port = spawn_serve(store, port_file, plan_path=plan_path,
                             state_every=1)
    acked_rows = 0
    killed_at = None
    try:
        with ServeClient(port=port) as c:
            for i, lo in enumerate(range(0, n, batch)):
                try:
                    r = c.ingest(items[lo:lo + batch], timeout_s=120)
                    assert r["ok"], r
                    acked_rows = lo + batch
                except Exception:  # noqa: BLE001 — the kill severs the socket; any transport error is the signal
                    killed_at = i
                    break
    finally:
        rc = proc.wait(timeout=120)
    assert killed_at == kill_batch, \
        f"kill fired at batch {killed_at}, planned {kill_batch} (rc={rc})"
    assert rc == -signal.SIGKILL, f"driver rc={rc}, wanted SIGKILL"
    assert acked_rows == kill_batch * batch
    # Flight recorder: the kill seat's last words.  The fault plane dumps
    # the ring + metrics to the daemon's flight dir (its store dir)
    # BEFORE SIGKILLing itself, and the dump's terminal span names the
    # seat that fired — the post-mortem contract.
    flights = sorted(glob.glob(os.path.join(store, "flight_*.json")))
    assert flights, "kill seat left no flight recorder dump"
    with open(flights[-1], encoding="utf-8") as f:
        flight = json.load(f)
    assert flight["reason"] == "fault.kill", flight["reason"]
    assert flight["site"] == "serve.ingest.commit", flight["site"]
    last = flight["spans"][-1]
    assert last["name"] == "flight.fault.kill", last
    assert last["tags"].get("site") == "serve.ingest.commit", last
    assert flight["metrics"]["counters"], "flight dump lost the registry"
    # Restart on the same store, NO fault plan: every acknowledged row
    # must still be served (known=True) — zero lost acked rows.
    os.remove(port_file)
    proc2, port2 = spawn_serve(store, port_file)
    try:
        with ServeClient(port=port2) as c:
            resp = c.query(items[:acked_rows])
            lost = int((~resp["known"]).sum())
            assert lost == 0, f"{lost} acknowledged rows lost to SIGKILL"
            # Re-ingest from the first unacknowledged batch on (the
            # killed batch recomputes; acked rows dedupe in the store)
            # and assert full elementwise parity with the cold run.
            for lo in range(acked_rows, n, batch):
                c.ingest(items[lo:lo + batch], timeout_s=120)
            c.quiesce(timeout_s=120)
            final = c.query(items)
            assert bool(final["known"].all())
            assert np.array_equal(final["labels"], cold), \
                "post-recovery serving labels diverged from cold batch"
            status = c.status()
            c.shutdown()
    finally:
        rc2 = proc2.wait(timeout=120)
    assert rc2 == 0, rc2
    return {"acked_before_kill": acked_rows, "lost_acked": 0,
            "rows": int(status["rows"]),
            "generation": int(status["generation"])}


def spawn_shard(root: str, sid: int, plan_path: str | None = None,
                timeout_s: float = 180.0) -> tuple:
    """Start one digest-range shard daemon (chaos_drivers ``shard``);
    returns (proc, port) once its ``serve_NNNN.port`` file lands."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("TSE1M_FAULT_PLAN", None)
    if plan_path:
        env["TSE1M_FAULT_PLAN"] = plan_path
    port_file = os.path.join(root, f"serve_{sid:04d}.port")
    if os.path.exists(port_file):  # never race a stale port
        os.remove(port_file)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "chaos_drivers.py"),
         "shard", "--root", root, "--range", str(sid)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(port_file):
            with open(port_file, encoding="utf-8") as f:
                txt = f.read().strip()
            if txt:
                return proc, int(txt)
        if proc.poll() is not None:
            out, err = proc.communicate(timeout=10)
            raise AssertionError(
                f"shard {sid} died before binding (rc={proc.returncode})"
                f"\n{err[-3000:]}")
        time.sleep(0.05)
    proc.kill()
    raise AssertionError(f"shard {sid} never wrote its port file")


def _oracle_sharded_run(items: "np.ndarray", batch: int,
                        oracle_root: str) -> tuple:
    """The uninterrupted ORACLE: the same batches through the same
    router logic over in-process shard daemons (LocalTransport) — the
    chaos run's post-recovery labels must equal these elementwise."""
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.serve import LocalTransport, ServeDaemon, ShardRouter

    params = ClusterParams(**SERVE_PARAMS)
    daemons = {
        sid: ServeDaemon(os.path.join(oracle_root, f"range_{sid:04d}"),
                         params=params, state_commit_every=1).start()
        for sid in range(2)}
    try:
        router = ShardRouter({sid: LocalTransport(d)
                              for sid, d in daemons.items()})
        for i, lo in enumerate(range(0, len(items), batch)):
            r = router.ingest(items[lo:lo + batch], timeout=300,
                              request_id=f"b{i:04d}")
            assert r["ok"], r
        router.quiesce(timeout=600)
        final = router.query(items)
        rows = sum(int(d._index.n_rows) for d in daemons.values())
    finally:
        for d in daemons.values():
            d.stop(commit=False)
    assert bool(final["known"].all())
    return final["labels"], rows


def sharded_kill_round(tmp: str, n: int = 600, batch: int = 100,
                       kill_batch: int = 2, seed: int = 13) -> dict:
    """The sharded-failover game-day, shared by pytest and the CI
    fault-matrix ``router-shard-kill`` seat: SIGKILL shard 0 at its
    ``serve.ingest.commit`` seat mid-round while the parent ingests
    through a ShardRouter over TCP.  A watcher respawns the replacement
    writer (which claims the range's next lease epoch); the router's
    retried in-flight slice — SAME request id — lands on it, so the
    round completes with ZERO lost acked rows, zero double-absorbed
    batches, and post-recovery labels elementwise-equal to an
    uninterrupted sharded run."""
    import threading

    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.resilience import RetryPolicy
    from tse1m_tpu.serve import ShardRouter, TcpTransport

    items, _ = synth_session_sets(n, set_size=64, seed=seed)
    oracle_labels, oracle_rows = _oracle_sharded_run(
        items, batch, os.path.join(tmp, "oracle_root"))

    root = os.path.join(tmp, "sharded_root")
    os.makedirs(root, exist_ok=True)
    plan_path = os.path.join(tmp, "plan.json")
    with open(plan_path, "w", encoding="utf-8") as f:
        json.dump({"rules": [{"site": "serve.ingest.commit",
                              "kind": "kill",
                              "after_calls": kill_batch}]}, f)
    procs = {}
    procs[0], _ = spawn_shard(root, 0, plan_path=plan_path)
    procs[1], _ = spawn_shard(root, 1)
    victim = procs[0]
    respawned = {}

    def watch_and_respawn() -> None:
        victim.wait()
        # The replacement writer claims epoch+1 on range 0; were the
        # victim a wedged zombie instead of a corpse, its next commit
        # would self-fence (coordinator.RangeLeaseGuard.verify).
        respawned["proc"], respawned["port"] = spawn_shard(root, 0)

    watcher = threading.Thread(target=watch_and_respawn, daemon=True)
    watcher.start()
    # The retry window must cover the replacement's cold start (a fresh
    # interpreter importing jax) — an operator tunes exactly this knob.
    router = ShardRouter(
        {sid: TcpTransport(
            port_file=os.path.join(root, f"serve_{sid:04d}.port"))
         for sid in range(2)},
        retry=RetryPolicy(max_attempts=60, base_delay=0.25, max_delay=3.0))
    acks = []
    try:
        for i, lo in enumerate(range(0, n, batch)):
            r = router.ingest(items[lo:lo + batch], timeout=300,
                              request_id=f"b{i:04d}")
            assert r["ok"], r
            acks.append(r)
        watcher.join(timeout=180)
        assert not watcher.is_alive(), "watcher never saw the kill"
        assert victim.returncode == -signal.SIGKILL, victim.returncode
        # Durability: every acked row answers through the router.
        final = router.query(items)
        lost = int((~final["known"]).sum())
        assert lost == 0, f"{lost} acked rows lost across the failover"
        assert np.array_equal(final["labels"], oracle_labels), \
            "post-failover labels diverged from the uninterrupted run"
        router.quiesce(timeout=600)
        status = router.status()
        assert status["ok"], status
        rows = sum(int(s["rows"]) for s in status["shard_status"].values())
        # Zero double-absorb: the killed batch recomputed exactly once.
        assert rows == oracle_rows, (rows, oracle_rows)
    finally:
        for proc in [procs[1], respawned.get("proc") or victim]:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
    return {"lost_acked": 0, "rows": rows, "oracle_rows": oracle_rows,
            "acked_batches": len(acks),
            "replayed_acks": sum(1 for a in acks if a.get("replayed"))}


__all__ = ["SERVE_PARAMS", "serve_kill_round", "sharded_kill_round",
           "spawn_serve", "spawn_shard"]
