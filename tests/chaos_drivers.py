"""Subprocess drivers for the SIGKILL chaos tests (test_chaos.py).

Each driver runs a *production* code path (CsvBatchCheckpointer collector
loop; cluster_sessions_resumable) with checkpoint/resume semantics.  The
kill comes from the fault plane: the parent test points TSE1M_FAULT_PLAN
at a plan whose rule is ``kind=kill`` at a checkpoint site, so the
process SIGKILLs itself mid-write — a real hard kill at a deterministic
point, with zero test-only branches in the code under test.
"""

from __future__ import annotations

import argparse
import sys


def run_csv(args) -> int:
    """Collector-shaped loop: emit records 0..n-1 through the batch
    checkpointer, skipping ids already durable in batch files (the
    processed-id resume pattern), then merge."""
    from tse1m_tpu.collect.checkpoint import (CsvBatchCheckpointer,
                                              processed_ids_from_csvs)

    done = processed_ids_from_csvs(args.dir, id_column="id")
    ck = CsvBatchCheckpointer(args.dir, "chaos", batch_size=args.batch,
                              fieldnames=["id", "value"])
    for i in range(args.n):
        if i in done:
            continue
        ck.add({"id": i, "value": f"v{i * i}"})
    ck.merge(args.final)
    return 0


def run_cluster(args) -> int:
    """Resumable clustering over a deterministic synthetic study; labels
    land in ``--out`` as .npy for the parent to compare.  ``--no-overlap``
    disables the double-buffered producer thread (the sequential oracle
    for the overlap chaos test); ``--info`` dumps the run's
    last_run_info — including the observability stage record — as JSON."""
    import json

    import numpy as np

    from tse1m_tpu.cluster import ClusterParams, cluster_sessions_resumable
    from tse1m_tpu.cluster.pipeline import last_run_info
    from tse1m_tpu.data.synth import synth_session_sets

    items = synth_session_sets(args.n, set_size=16, seed=args.seed)[0]
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                           h2d_chunks=4, overlap=not args.no_overlap)
    labels = cluster_sessions_resumable(items, params,
                                        checkpoint_dir=args.dir)
    np.save(args.out, labels)
    if args.info:
        with open(args.info, "w") as f:
            json.dump(dict(last_run_info), f)
    return 0


def run_store(args) -> int:
    """Store-enabled clustering (cluster/store.py): populate or warm
    against ``--store-dir``; labels land in ``--out`` as .npy.  The chaos
    tests SIGKILL this mid store-shard write (site ``store.sig.save``) or
    mid state commit (``store.state.save``) and assert the next run
    detects the torn artifact, recomputes, and produces labels identical
    to an uninterrupted storeless run."""
    import json

    import numpy as np

    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.cluster.pipeline import last_run_info
    from tse1m_tpu.data.synth import synth_session_sets

    items = synth_session_sets(args.n, set_size=16, seed=args.seed)[0]
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                           sig_store=args.store_dir)
    labels = cluster_sessions(items, params)
    np.save(args.out, labels)
    if args.info:
        with open(args.info, "w") as f:
            json.dump({k: v for k, v in last_run_info.items()
                       if k != "stages"}, f)
    return 0


def run_pod(args) -> int:
    """Pod-supervised store-enabled clustering (cli.run_pod_cluster):
    under TSE1M_NUM_PROCESSES/…_PROCESS_ID each spawned process takes
    its pod identity straight from the env — jax.distributed is NEVER
    initialized, so no XLA coordination client exists to fatal a
    survivor when a peer (including the leader) dies.  Each process
    shards the signature store by digest range, beats heartbeats, holds
    epoch leases and supervises its peers — the production pod path,
    end to end.  The chaos/CI drivers SIGKILL, wedge (``hostloss``) or
    wedge-then-wake (``zombie``) one worker mid-run and assert the
    survivor fails over (promoting itself when the leader died) while
    any woken zombie self-fences: labels land in ``--out`` (.npy), run
    info in ``--info``, and manifest fragments + the merged manifest
    under ``--result-dir``."""
    import json
    import os

    # Platform pin must precede the first backend touch (the image's
    # sitecustomize may pin a TPU plugin — same dance as
    # tests/test_multihost_multiprocess.py's worker).
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from tse1m_tpu.cli import run_pod_cluster
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.cluster.pipeline import last_run_info
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.observability.merge import (fragment_manifest_path,
                                               merge_run_manifests)
    from tse1m_tpu.parallel import multihost
    from tse1m_tpu.resilience import StepRunner

    items = synth_session_sets(args.n, set_size=16, seed=args.seed)[0]
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                           sig_store=args.store_dir)
    nproc, pid = multihost.pod_process_env()
    if args.result_dir and nproc > 1:
        manifest_path = fragment_manifest_path(args.result_dir, pid)
    elif args.result_dir:
        manifest_path = os.path.join(args.result_dir, "run_manifest.json")
    else:
        manifest_path = None
    runner = StepRunner(manifest_path)
    box = {}

    def step() -> dict:
        labels, pod = run_pod_cluster(items, args.n, params)
        box["labels"] = labels
        return {**pod, **{k: v for k, v in last_run_info.items()
                          if k != "stages"}}

    rec = runner.run("pod-cluster", step)
    if (rec.result or {}).get("pod_epoch") is not None:
        runner.set_meta(epoch=rec.result["pod_epoch"])
    if args.result_dir and nproc > 1:
        survivor = (rec.result or {}).get("pod_survivor")
        if pid == 0 or survivor == pid:
            from tse1m_tpu.cli import _await_fragments

            _await_fragments(args.result_dir, nproc)
            merge_run_manifests(args.result_dir, nproc)
    if rec.status != "ok":
        return 1
    np.save(args.out, box["labels"])
    if args.info:
        with open(args.info, "w") as f:
            json.dump(rec.result, f)
    print("POD_OK", pid, flush=True)
    return 0


def run_compact(args) -> int:
    """Fold a store's shards (SignatureStore.compact).  The chaos test
    SIGKILLs this at ``store.compact.save`` — compacted temps written,
    manifest not yet committed — and asserts the next open sweeps the
    temps, keeps the old shards, and warm labels still match."""
    from tse1m_tpu.cluster.store import SignatureStore

    store = SignatureStore.open_existing(args.store_dir)
    folded = store.compact()
    print(f"compacted {folded} shards")
    return 0


def run_serve(args) -> int:
    """Serving daemon subprocess (ingest loop + TCP API) for the serve
    chaos tests: the parent ingests batches through a ServeClient while
    a fault plan SIGKILLs this process at ``serve.ingest.commit`` —
    mid-batch, BEFORE the store append commits — and then asserts a
    restarted daemon still answers every previously-ACKNOWLEDGED row
    (zero lost acked rows; the un-acked batch recomputes)."""
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.serve import ServeDaemon, ServeServer, SloPolicy

    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
    daemon = ServeDaemon(args.store_dir, params=params,
                         slo=SloPolicy(max_backlog_batches=args.backlog),
                         state_commit_every=args.state_every).start()
    server = ServeServer(daemon, port=0)
    try:
        server.serve_until_shutdown(port_file=args.port_file)
    finally:
        server.server_close()
        daemon.stop()
    print("SERVE_OK", flush=True)
    return 0 if daemon._ingest_error is None else 1


def run_shard(args) -> int:
    """Digest-range shard daemon for the SHARDED serve chaos round: a
    single-writer ServeDaemon over ``<root>/range_NNNN``, fenced by the
    range's epoch lease (a respawned replacement claims the next epoch,
    so a surviving zombie of this process would self-fence with zero
    rows written), heartbeating for the router's PeerMonitor and
    committing state every generation so the replacement preserves
    local row identity for every acked batch.  The parent routes
    through tse1m_tpu.serve.ShardRouter and SIGKILLs this process at
    ``serve.ingest.commit`` via TSE1M_FAULT_PLAN."""
    import os

    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.resilience.coordinator import (HeartbeatWriter,
                                                  RangeLeaseGuard)
    from tse1m_tpu.serve import ServeDaemon, ServeServer

    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
    store = os.path.join(args.root, f"range_{args.range:04d}")
    guard = RangeLeaseGuard.claim(args.root, args.range, owner=os.getpid())
    heartbeat = HeartbeatWriter(args.root, process_id=args.range).start()
    daemon = ServeDaemon(store, params=params, state_commit_every=1,
                         lease_guard=guard).start()
    server = ServeServer(daemon, port=0)
    port_file = args.port_file or os.path.join(
        args.root, f"serve_{args.range:04d}.port")
    try:
        server.serve_until_shutdown(port_file=port_file)
    finally:
        server.server_close()
        daemon.stop()
        heartbeat.stop()
    print("SHARD_OK", flush=True)
    return 0 if daemon._ingest_error is None else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("csv")
    p.add_argument("--dir", required=True)
    p.add_argument("--final", required=True)
    p.add_argument("--n", type=int, default=20)
    p.add_argument("--batch", type=int, default=4)
    p.set_defaults(fn=run_csv)

    p = sub.add_parser("cluster")
    p.add_argument("--dir", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--n", type=int, default=2048)
    p.add_argument("--seed", type=int, default=13)
    p.add_argument("--no-overlap", action="store_true")
    p.add_argument("--info", default=None)
    p.set_defaults(fn=run_cluster)

    p = sub.add_parser("store")
    p.add_argument("--store-dir", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--seed", type=int, default=13)
    p.add_argument("--info", default=None)
    p.set_defaults(fn=run_store)

    p = sub.add_parser("pod")
    p.add_argument("--store-dir", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--n", type=int, default=800)
    p.add_argument("--seed", type=int, default=13)
    p.add_argument("--info", default=None)
    p.add_argument("--result-dir", default=None)
    p.set_defaults(fn=run_pod)

    p = sub.add_parser("compact")
    p.add_argument("--store-dir", required=True)
    p.set_defaults(fn=run_compact)

    p = sub.add_parser("serve")
    p.add_argument("--store-dir", required=True)
    p.add_argument("--port-file", required=True)
    p.add_argument("--state-every", type=int, default=2)
    p.add_argument("--backlog", type=int, default=64)
    p.set_defaults(fn=run_serve)

    p = sub.add_parser("shard")
    p.add_argument("--root", required=True)
    p.add_argument("--range", type=int, required=True)
    p.add_argument("--port-file", default=None)
    p.set_defaults(fn=run_shard)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
