"""Batched scoring plane (cluster/kernels/score.py): three-way
bit-parity (numpy oracle / jnp fori_loop reference / pallas interpret),
top-k rank parity across signature schemes x quant bits, the streamed
store scan vs a single-shot host oracle, and the zero-recompile
steady-state contract the bench topk round gates."""

from __future__ import annotations

import numpy as np
import pytest

from tse1m_tpu.cluster.encode import quantize_ids
from tse1m_tpu.cluster.kernels.score import (K_PAD, bulk_topk_store,
                                             score_topk_host,
                                             store_scan_locator,
                                             topk_agreement)
from tse1m_tpu.cluster.schemes import make_params, scheme_host_signatures
from tse1m_tpu.cluster.store import SignatureStore

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # property tests degrade to the deterministic suite
    HAVE_HYPOTHESIS = False

H = 16


def _sigs(n: int, seed: int, scheme: str = "kminhash",
          qbits: int = 0, width: int = 12) -> np.ndarray:
    """[n, H] uint32 signatures through the real scheme kernels (host
    mirror — bit-identical to the device paths by the schemes.py
    contract), over optionally quantized synthetic coverage rows."""
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, 2**32, size=(n, width), dtype=np.uint32)
    if qbits:
        rows = quantize_ids(rows, qbits)
    return scheme_host_signatures(rows, make_params(scheme, H, seed=7))


def _assert_topk_equal(a, b) -> None:
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])


# -- three-way parity across schemes x quant bits ----------------------------

@pytest.mark.parametrize("scheme", ("kminhash", "cminhash", "weighted"))
@pytest.mark.parametrize("qbits", (0, 10, 8))
def test_three_way_parity(scheme, qbits):
    q = _sigs(7, 1, scheme, qbits)
    s = _sigs(300, 2, scheme, qbits)
    ref = score_topk_host(q, s, 5)
    _assert_topk_equal(topk_agreement(q, s, 5, use_pallas="never"), ref)
    _assert_topk_equal(topk_agreement(q, s, 5, use_pallas="interpret"),
                       ref)


def test_exact_duplicates_rank_first():
    s = _sigs(64, 3)
    q = s[[10, 41]].copy()
    counts, rows = score_topk_host(q, s, 3)
    assert rows[0, 0] == 10 and rows[1, 0] == 41
    assert counts[0, 0] == H and counts[1, 0] == H
    _assert_topk_equal(topk_agreement(q, s, 3, use_pallas="never"),
                       (counts, rows))


# -- edge cases (identical across all implementations) -----------------------

def test_empty_query_batch():
    s = _sigs(32, 4)
    q = np.zeros((0, H), np.uint32)
    for got in (score_topk_host(q, s, 4),
                topk_agreement(q, s, 4, use_pallas="never"),
                topk_agreement(q, s, 4, use_pallas="interpret")):
        assert got[0].shape == (0, 4) and got[1].shape == (0, 4)


def test_k_larger_than_store():
    q = _sigs(3, 5)
    s = _sigs(6, 6)
    ref = score_topk_host(q, s, 10)
    # slots past n_rows pad with (-1, -1) in every implementation
    assert (ref[0][:, 6:] == -1).all() and (ref[1][:, 6:] == -1).all()
    assert (ref[1][:, :6] >= 0).all()
    _assert_topk_equal(topk_agreement(q, s, 10, use_pallas="never"), ref)
    _assert_topk_equal(topk_agreement(q, s, 10, use_pallas="interpret"),
                       ref)


def test_all_ties_resolve_to_ascending_rows():
    # Every store row identical: counts tie everywhere, so the
    # determinism contract (-count, ascending row) must yield 0..k-1.
    s = np.tile(_sigs(1, 7), (40, 1))
    q = _sigs(4, 8)
    ref = score_topk_host(q, s, 6)
    np.testing.assert_array_equal(
        ref[1], np.tile(np.arange(6, dtype=np.int32), (4, 1)))
    _assert_topk_equal(topk_agreement(q, s, 6, use_pallas="never"), ref)
    _assert_topk_equal(topk_agreement(q, s, 6, use_pallas="interpret"),
                       ref)


def test_k_beyond_state_tile_refuses():
    q, s = _sigs(2, 9), _sigs(8, 10)
    for fn in (lambda: score_topk_host(q, s, K_PAD + 1),
               lambda: topk_agreement(q, s, K_PAD + 1),
               lambda: score_topk_host(q, s, -1)):
        with pytest.raises(ValueError):
            fn()


# -- streamed store scan -----------------------------------------------------

def _build_store(tmp_path, parts, seed0=20):
    store = SignatureStore(str(tmp_path / "s"),
                           {"n_hashes": H, "seed": 7, "quant_bits": 0,
                            "scheme": "kminhash"})
    rng = np.random.default_rng(99)
    blocks = []
    for i, n in enumerate(parts):
        sigs = _sigs(n, seed0 + i)
        digests = rng.integers(0, 2**64, size=(n, 2), dtype=np.uint64)
        assert store.append(digests, sigs) == n
        blocks.append(sigs)
    return store, blocks


def test_bulk_scan_matches_host_oracle(tmp_path):
    store, blocks = _build_store(tmp_path, (130, 70, 41))
    ordered = [b for _, b in sorted(
        zip((int(e["id"]) for e in store.shards), blocks),
        key=lambda t: t[0])]
    all_sigs = np.concatenate(ordered)
    q = _sigs(5, 30)
    ref = score_topk_host(q, all_sigs, 7)
    for overlap in (True, False):
        got = bulk_topk_store(store, q, 7, use_pallas="never",
                              chunk_rows=64, overlap=overlap)
        _assert_topk_equal(got, ref)
    # the locator inverts the scan-global row space
    rows = got[1][got[1] >= 0]
    loc = store_scan_locator(store, rows)
    back = store.load_signatures(loc[:, 0], loc[:, 1])
    np.testing.assert_array_equal(back, all_sigs[rows])


def test_bulk_scan_empty_store(tmp_path):
    store = SignatureStore(str(tmp_path / "s"),
                           {"n_hashes": H, "seed": 7, "quant_bits": 0,
                            "scheme": "kminhash"})
    counts, rows = bulk_topk_store(store, _sigs(3, 31), 4,
                                   use_pallas="never")
    assert (counts == -1).all() and (rows == -1).all()


def test_bulk_scan_steady_state_sanitizer_clean(tmp_path):
    # The acceptance contract: after one warm pass, a repeat scan with
    # the same (query pow2 pad, k, chunk) shapes runs with ZERO
    # compiles and only the scorer's explicit wire-layer transfers.
    from tse1m_tpu.lint.runtime import sanitized

    store, _ = _build_store(tmp_path, (150, 90))
    q = _sigs(6, 32)
    warm = bulk_topk_store(store, q, 5, use_pallas="never", chunk_rows=64)
    with sanitized(0):
        hot = bulk_topk_store(store, q, 5, use_pallas="never",
                              chunk_rows=64)
    _assert_topk_equal(hot, warm)


# -- property tests (hypothesis) ---------------------------------------------

if HAVE_HYPOTHESIS:

    _sig_arrays = st.integers(min_value=0, max_value=2**32 - 1)

    @settings(max_examples=25, deadline=None)
    @given(st.data())
    def test_device_host_rank_parity_property(data):
        nq = data.draw(st.integers(0, 6), label="nq")
        n = data.draw(st.integers(0, 40), label="n_rows")
        k = data.draw(st.integers(0, 12), label="k")
        # Tiny value universe forces heavy agreement-count ties — the
        # hard case for the (-count, ascending row) determinism rule.
        lo = data.draw(st.integers(0, 3), label="universe")
        rng = np.random.default_rng(data.draw(_sig_arrays, label="seed"))
        q = rng.integers(0, 2 + lo, size=(nq, H)).astype(np.uint32)
        s = rng.integers(0, 2 + lo, size=(n, H)).astype(np.uint32)
        ref = score_topk_host(q, s, k)
        _assert_topk_equal(topk_agreement(q, s, k, use_pallas="never"),
                           ref)
        _assert_topk_equal(
            topk_agreement(q, s, k, use_pallas="interpret"), ref)

    @settings(max_examples=10, deadline=None)
    @given(st.sampled_from(("kminhash", "cminhash", "weighted")),
           st.sampled_from((0, 10, 8)), _sig_arrays)
    def test_scheme_quant_parity_property(scheme, qbits, seed):
        q = _sigs(4, seed % 1000, scheme, qbits)
        s = _sigs(60, seed % 997 + 1, scheme, qbits)
        ref = score_topk_host(q, s, 6)
        _assert_topk_equal(topk_agreement(q, s, 6, use_pallas="never"),
                           ref)
        _assert_topk_equal(
            topk_agreement(q, s, 6, use_pallas="interpret"), ref)

else:  # pragma: no cover - environment without hypothesis

    @pytest.mark.skip(reason="property tests need hypothesis "
                             "(pip install -e .[test])")
    def test_device_host_rank_parity_property():
        pass

    @pytest.mark.skip(reason="property tests need hypothesis "
                             "(pip install -e .[test])")
    def test_scheme_quant_parity_property():
        pass
