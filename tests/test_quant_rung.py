"""The second degradation rung: RESOURCE_EXHAUSTED first drops
`wire_quant_bits` one step down the b-bit ladder (arXiv:1205.2958 — 8-10
bits retain accuracy) BEFORE chunk-halving, persists the surviving width
to the machine calibration, clamps checkpointed resumes to the surviving
policy, and restores full fidelity once the device heals (a clean run at
the degraded width)."""

from __future__ import annotations

import os
from dataclasses import replace

import numpy as np
import pytest

from tse1m_tpu.cluster import ClusterParams, cluster_sessions
from tse1m_tpu.cluster.pipeline import (_degraded_quant_floor,
                                        _next_quant_rung,
                                        _persist_quant_bits,
                                        cluster_sessions_resumable,
                                        last_run_info)
from tse1m_tpu.data.synth import synth_session_sets
from tse1m_tpu.observability import pop_degradation_events
from tse1m_tpu.resilience.faults import FaultPlan

PARAMS = dict(n_hashes=32, n_bands=4, use_pallas="never")


@pytest.fixture(autouse=True)
def _isolated_calibration(tmp_path, monkeypatch):
    monkeypatch.setenv("TSE1M_ROUTER_CAL",
                       os.path.join(str(tmp_path), "cal.json"))
    pop_degradation_events()
    yield
    pop_degradation_events()


def _oom_plan(times: int = 1) -> FaultPlan:
    return FaultPlan.from_dict({"rules": [{
        "site": "pipeline.h2d", "kind": "raise", "times": times,
        "message": "RESOURCE_EXHAUSTED: injected allocation failure"}]})


def test_next_quant_rung_ladder():
    assert _next_quant_rung(0) == 10    # quantization off -> first rung
    assert _next_quant_rung(16) == 10
    assert _next_quant_rung(10) == 8
    assert _next_quant_rung(8) is None  # out of rungs -> chunk halving


def test_oom_drops_quant_bits_before_halving():
    items = synth_session_sets(400, set_size=16, seed=13)[0]
    with _oom_plan().active():
        labels = cluster_sessions(items, ClusterParams(**PARAMS))
    events = pop_degradation_events()
    kinds = [e["kind"] for e in events]
    assert "quant_drop" in kinds
    assert "chunk_halving" not in kinds  # the quant rung fired FIRST
    drop = next(e for e in events if e["kind"] == "quant_drop")
    assert drop["detail"]["to_bits"] == 10
    assert last_run_info["wire_quant_bits"] == 10
    assert last_run_info["quant_drops"] == 1
    # surviving width persisted: the next run starts degraded
    assert _degraded_quant_floor() == 10
    # label parity with an explicit 10-bit run: the whole stream
    # restarted in one universe, no mixed-width chunks
    ref = cluster_sessions(items, ClusterParams(**PARAMS,
                                                wire_quant_bits=10))
    np.testing.assert_array_equal(labels, ref)


def test_degraded_floor_clamps_then_restores_on_heal():
    items = synth_session_sets(300, set_size=16, seed=7)[0]
    _persist_quant_bits(10)
    cluster_sessions(items, ClusterParams(**PARAMS))  # clean, clamped
    assert last_run_info["wire_quant_bits"] == 10
    events = pop_degradation_events()
    assert any(e["kind"] == "quant_restore" for e in events)
    assert _degraded_quant_floor() == 0  # device healed: floor cleared
    cluster_sessions(items, ClusterParams(**PARAMS))
    assert last_run_info["wire_quant_bits"] == 0  # full fidelity again


def test_store_runs_never_quant_drop(tmp_path):
    """The store policy key pins quant_bits, so store-enabled runs must
    answer OOM with chunk halving — never a mid-run universe change."""
    items = synth_session_sets(400, set_size=16, seed=3)[0]
    params = ClusterParams(**PARAMS,
                           sig_store=os.path.join(str(tmp_path), "store"))
    with _oom_plan().active():
        cluster_sessions(items, params)
    kinds = [e["kind"] for e in pop_degradation_events()]
    assert "chunk_halving" in kinds
    assert "quant_drop" not in kinds
    assert last_run_info["wire_quant_bits"] == 0


def test_checkpoint_resume_clamps_to_surviving_policy(tmp_path):
    """A checkpoint written under a (degraded or explicit) quant width
    must resume under an AUTO policy by adopting that width — the shards
    hold signatures of that universe.  Explicit mismatches still refuse."""
    items = synth_session_sets(300, set_size=16, seed=5)[0]
    ckpt = os.path.join(str(tmp_path), "ckpt")
    p10 = ClusterParams(**PARAMS, wire_quant_bits=10)
    first = cluster_sessions_resumable(items, p10, checkpoint_dir=ckpt,
                                       cleanup=False)
    # an explicit DIFFERENT width still refuses (changed-policy guard)
    with pytest.raises(ValueError):
        cluster_sessions_resumable(
            items, replace(p10, wire_quant_bits=8), checkpoint_dir=ckpt)
    # auto adopts the surviving 10-bit policy instead of refusing
    second = cluster_sessions_resumable(
        items, ClusterParams(**PARAMS), checkpoint_dir=ckpt)
    np.testing.assert_array_equal(first, second)


def test_checkpoint_resume_unquantized_ignores_floor(tmp_path):
    """A floor persisted AFTER an unquantized checkpoint was written
    must not re-plan the resume into a different universe."""
    items = synth_session_sets(300, set_size=16, seed=9)[0]
    ckpt = os.path.join(str(tmp_path), "ckpt")
    first = cluster_sessions_resumable(items, ClusterParams(**PARAMS),
                                       checkpoint_dir=ckpt, cleanup=False)
    _persist_quant_bits(10)  # degradation happened elsewhere meanwhile
    second = cluster_sessions_resumable(items, ClusterParams(**PARAMS),
                                        checkpoint_dir=ckpt)
    np.testing.assert_array_equal(first, second)
