"""End-to-end CLI runs in a subprocess — the user-facing entry points.

Everything else in the suite calls drivers as functions; these tests cover
what a user actually types (`python -m tse1m_tpu.cli ...`), including
argument parsing, config plumbing, exit codes, and artifact placement —
the rebuild's equivalent of the reference's documented flow
(README.md "Run Analysis Programs": run_all_analysis.sh / rq scripts).
Scale is tiny so the whole flow stays a few seconds on the CPU mesh.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest


def run_cli(args, cwd, env_extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    env.update(env_extra or {})
    return subprocess.run([sys.executable, "-m", "tse1m_tpu.cli", *args],
                         cwd=cwd, env=env, capture_output=True, text=True,
                         timeout=600)


@pytest.fixture(scope="module")
def workdir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli_e2e")
    # The CLI resolves data/result paths relative to the cwd; symlink the
    # package by running from the repo root but pointing --db at tmp.
    return str(d)


@pytest.fixture(scope="module")
def synth_db(workdir):
    db = os.path.join(workdir, "cli.sqlite")
    proc = run_cli(["synth", "--db", db, "--projects", "8", "--days", "400",
                    "--seed", "4"], cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.path.exists(db)
    return db


def test_cli_stats(synth_db):
    proc = run_cli(["stats", "--db", synth_db], cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "projects" in proc.stdout.lower()


@pytest.mark.slow
def test_cli_all_runs_every_rq(synth_db, workdir):
    out = os.path.join(workdir, "results")
    proc = run_cli(["all", "--db", synth_db, "--backend", "jax_tpu",
                    "--result-dir", out], cwd="/root/repo")
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    for artifact in (
        "rq1/rq1_detection_rate_stats.csv",
        "rq2/coverage_by_session_index.csv",
        "rq3/all_coverage_change_analysis.csv",
        "rq3/detected_coverage_changes.csv",
        "rq4/bug/rq4_g1_g2_detection_trend.csv",
        "rq4/coverage/g2_g1_trend_stats.csv",
    ):
        path = os.path.join(out, artifact)
        assert os.path.exists(path), f"missing {artifact}"
    # Every RQ leaves a manifest recording backend + timings.
    man = os.path.join(out, "rq1", "rq1_manifest.json")
    with open(man) as f:
        recorded = json.load(f)
    assert recorded.get("backend") == "jax_tpu"


@pytest.mark.slow
def test_cli_all_survives_permanent_rq_fault(synth_db, workdir):
    """ISSUE acceptance: with a permanent fault in one RQ's inputs
    (corpus CSV missing => rq4a/rq4b raise), `cli all` still runs the
    remaining RQs, records the failures in run_manifest.json, and exits
    nonzero."""
    out = os.path.join(workdir, "results_faulty")
    proc = run_cli(["all", "--db", synth_db, "--result-dir", out],
                   cwd="/root/repo",
                   env_extra={"TSE1M_CORPUS_CSV":
                              os.path.join(workdir, "nope", "missing.csv")})
    assert proc.returncode != 0
    with open(os.path.join(out, "run_manifest.json")) as f:
        payload = json.load(f)
    by_name = {s["name"]: s for s in payload["steps"]}
    assert payload["ok"] is False
    # the corpus-dependent RQs failed; every other RQ still completed
    assert by_name["rq4a"]["status"] == "failed"
    assert by_name["rq4a"]["traceback"]
    for name in ("rq1", "rq2a", "rq2b", "rq3"):
        assert by_name[name]["status"] == "ok", by_name[name]
    assert os.path.exists(
        os.path.join(out, "rq1", "rq1_detection_rate_stats.csv"))


@pytest.mark.slow
def test_cli_all_under_transient_db_faults_matches_fault_free(synth_db,
                                                              workdir):
    """ISSUE acceptance: transient injected failures at the DB seat leave
    `cli all` output identical to a fault-free run (exit 0, all steps ok)."""
    plan = os.path.join(workdir, "plan.json")
    with open(plan, "w") as f:
        json.dump({"seed": 1, "rules": [
            {"site": "db.execute", "times": 3},
            {"site": "db.connect", "times": 1, "kind": "connection_drop",
             "after_calls": 1},
        ]}, f)
    out = os.path.join(workdir, "results_injected")
    proc = run_cli(["all", "--db", synth_db, "--result-dir", out],
                   cwd="/root/repo",
                   env_extra={"TSE1M_FAULT_PLAN": plan,
                              "TSE1M_RETRY_BASE_DELAY": "0.01"})
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    with open(os.path.join(out, "run_manifest.json")) as f:
        payload = json.load(f)
    assert payload["ok"] is True
    assert all(s["status"] == "ok" for s in payload["steps"])


@pytest.mark.slow
def test_cli_cluster_demo():
    proc = run_cli(["cluster", "--n", "4096", "--ari-sample", "1024"],
                   cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ari" in proc.stdout.lower()


def test_cli_rejects_unknown_backend(synth_db):
    proc = run_cli(["rq1", "--db", synth_db, "--backend", "cuda"],
                   cwd="/root/repo")
    assert proc.returncode != 0
