"""TRUE multi-process validation of the multihost layer: two worker
processes bring up `jax.distributed` over a local coordinator (gloo
collectives on CPU — the same wire path DCN collectives take on a pod),
each feeds only its process-local slice through `put_process_local`, and
the sharded clustering result must equal a plain single-process run of the
same (deterministic) study.

This is the strongest statement the repo can make about multi-host without
pod hardware: not a degenerate single-process pass, but real cross-process
device collectives through the production code path
(parallel/multihost.py -> cluster_sessions pre-sharded input ->
process_allgather materialisation).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

# Deliberately NOT a multiple of the 8-device mesh (2 processes x 4 virtual
# devices): a real study size never is one, so the padded feeding path
# (padded_row_count + put_process_local_padded) is what this validates.
N = 397
SEED = 5

WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    # Platform choice must precede the first backend init (this image's
    # sitecustomize pins a TPU plugin; see __graft_entry__.py).
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4"
                               ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    # Distributed init must precede ANY backend use — import order matters:
    # initialize first, then the modules whose imports may touch devices.
    from tse1m_tpu.parallel import multihost

    n, seed, out = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    assert multihost.initialize_from_env(), "distributed init did not engage"

    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets
    assert jax.process_count() == 2 and jax.device_count() == 8
    mesh = multihost.global_mesh()
    items, _ = synth_session_sets(n, set_size=16, seed=seed)
    lo, hi = multihost.local_row_range(multihost.padded_row_count(n, mesh))
    arr, n_pad = multihost.put_process_local_padded(
        np.ascontiguousarray(items[lo:min(hi, n)], dtype=np.uint32), n, mesh)
    assert n_pad % mesh.devices.size == 0
    labels = cluster_sessions(
        arr, ClusterParams(n_hashes=32, n_bands=4, use_pallas="never"),
        mesh=mesh)[:n]
    multihost.all_processes_ready("labels-done")

    # Flagship RQ on the same global mesh: every process builds the same
    # deterministic study and the sharded RQ1 kernel reduces across hosts.
    import tempfile
    from tse1m_tpu.backend.jax_backend import JaxBackend
    from tse1m_tpu.config import Config
    from tse1m_tpu.data.columnar import StudyArrays
    from tse1m_tpu.data.synth import SynthSpec, generate_study
    from tse1m_tpu.db.connection import DB

    with tempfile.TemporaryDirectory() as d:
        study = generate_study(SynthSpec(n_projects=6, days=380, seed=seed))
        cfg = Config(engine="sqlite",
                     sqlite_path=os.path.join(d, "s.sqlite"),
                     limit_date="2026-01-01")
        db = DB(config=cfg).connect()
        study.to_db(db)
        arrays = StudyArrays.from_db(db, cfg)
        db.closeConnection()
    limit_ns = int(np.datetime64(cfg.limit_date, "ns").astype(np.int64))
    backend = JaxBackend(mesh=mesh)
    rq1 = backend.rq1_detection(arrays, limit_ns, min_projects=2)
    # rq2 trends exercises the session/project-sharded percentile, mean,
    # Spearman and psum-count kernels (the P(None, AXIS) placements RQ1
    # never touches).
    rq2 = backend.rq2_trends(arrays, limit_ns)
    multihost.all_processes_ready("rq-done")
    np.savez(out, labels=labels, rq1_iterations=rq1.iterations,
             rq1_total=rq1.total_projects, rq1_detected=rq1.detected_counts,
             rq1_iter_of_issue=rq1.iteration_of_issue,
             rq1_link=rq1.link_idx,
             rq2_spearman=rq2.spearman, rq2_percentiles=rq2.percentiles,
             rq2_mean=rq2.mean, rq2_counts=rq2.counts)
    print("WORKER_OK", jax.process_index(), flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cluster_matches_single_process(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = []
    outs = [str(tmp_path / f"out_{p}.npz") for p in range(2)]
    for p in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # worker sets its own device count
        # Script-by-path puts the tmp dir (not cwd) on sys.path.
        env["PYTHONPATH"] = "/root/repo" + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        env.update({
            "TSE1M_COORDINATOR": f"127.0.0.1:{port}",
            "TSE1M_NUM_PROCESSES": "2",
            "TSE1M_PROCESS_ID": str(p),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(N), str(SEED), outs[p]],
            cwd="/root/repo", env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    results = [p.communicate(timeout=540) for p in procs]
    for p, (out, errtxt) in zip(procs, results):
        assert p.returncode == 0, (out[-2000:], errtxt[-2000:])
        assert "WORKER_OK" in out

    # Single-process oracles on the identical deterministic inputs.
    import tempfile

    from tse1m_tpu.backend.pandas_backend import PandasBackend
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.config import Config
    from tse1m_tpu.data.columnar import StudyArrays
    from tse1m_tpu.data.synth import (SynthSpec, generate_study,
                                      synth_session_sets)
    from tse1m_tpu.db.connection import DB

    items, _ = synth_session_sets(N, set_size=16, seed=SEED)
    want = cluster_sessions(
        items, ClusterParams(n_hashes=32, n_bands=4, use_pallas="never"))
    with tempfile.TemporaryDirectory() as d:
        study = generate_study(SynthSpec(n_projects=6, days=380, seed=SEED))
        cfg = Config(engine="sqlite",
                     sqlite_path=os.path.join(d, "s.sqlite"),
                     limit_date="2026-01-01")
        db = DB(config=cfg).connect()
        study.to_db(db)
        arrays = StudyArrays.from_db(db, cfg)
        db.closeConnection()
    limit_ns = int(np.datetime64(cfg.limit_date, "ns").astype(np.int64))
    rq1 = PandasBackend().rq1_detection(arrays, limit_ns, min_projects=2)
    from tse1m_tpu.backend.jax_backend import JaxBackend

    rq2 = JaxBackend(mesh=None).rq2_trends(arrays, limit_ns)

    for out_path in outs:
        got = np.load(out_path)
        np.testing.assert_array_equal(got["labels"], want)
        np.testing.assert_array_equal(got["rq1_iterations"], rq1.iterations)
        np.testing.assert_array_equal(got["rq1_total"], rq1.total_projects)
        np.testing.assert_array_equal(got["rq1_detected"],
                                      rq1.detected_counts)
        np.testing.assert_array_equal(got["rq1_iter_of_issue"],
                                      rq1.iteration_of_issue)
        np.testing.assert_array_equal(got["rq1_link"], rq1.link_idx)
        np.testing.assert_array_equal(got["rq2_spearman"], rq2.spearman)
        np.testing.assert_array_equal(got["rq2_percentiles"],
                                      rq2.percentiles)
        np.testing.assert_array_equal(got["rq2_mean"], rq2.mean)
        np.testing.assert_array_equal(got["rq2_counts"], rq2.counts)
