"""SQL-dump restore (db/restore.py + `cli restore`) — the reference's
`psql ... < backup_clean.sql` bootstrap (README.md:55) for holders of the
real dump, against either engine."""

from __future__ import annotations

import numpy as np
import pytest

from tse1m_tpu.config import Config
from tse1m_tpu.db.connection import DB
from tse1m_tpu.db.restore import restore_sql_dump

# A miniature pg_dump in its default (COPY) format: DDL/SET noise that
# must be skipped, COPY blocks for three study tables + one unknown
# table, escapes, NULLs, and array literals.
_PG_DUMP = r"""--
-- PostgreSQL database dump
--
SET statement_timeout = 0;
SET client_encoding = 'UTF8';
CREATE TABLE public.buildlog_data (
    name text NOT NULL,
    project text,
    timecreated timestamp with time zone
);
ALTER TABLE public.buildlog_data OWNER TO myuser;

COPY public.project_info (project, first_commit_datetime, language) FROM stdin;
zlib	2013-01-01 00:00:00	c
brotli	2014-02-03 10:00:00	c++
\.

COPY public.buildlog_data (name, project, timecreated, build_type, result, modules, revisions) FROM stdin;
log-1.txt	zlib	2023-06-01 01:00:00	Fuzzing	Finish	{zlib,libfuzzer}	{abc123,350000}
log-2.txt	zlib	2023-06-01 13:11:00	Coverage	Finish	{zlib,libfuzzer}	{abc123,350000}
log-3.txt	brotli	2023-06-02 02:00:00	Fuzzing	Error	\N	\N
log-4.txt	brotli	2023-06-02 03:00:00	Fuzzing	Finish	{brotli}	{tab\tin\tvalue,1}
\.

COPY public.issues (project, number, rts, status, crash_type, severity, type, regressed_build, new_id) FROM stdin;
zlib	1001	2023-06-01 05:00:00	Fixed	Heap-buffer-overflow READ	High	Vulnerability	{zlib-regress-1}	42001001
brotli	1002	2023-06-02 06:00:00	WontFix	Timeout	Low	Bug	\N	42001002
\.

COPY public.some_internal_table (a, b) FROM stdin;
1	2
\.

COPY public.total_coverage (project, date, coverage, covered_line, total_line) FROM stdin;
zlib	2023-06-01	45.5	4550	10000
brotli	2023-06-02	60.25	6025	10000
\.
"""

_INSERT_DUMP = """
SET search_path = public;
INSERT INTO project_info (project, first_commit_datetime, language)
    VALUES ('zlib', '2013-01-01 00:00:00', 'c');
INSERT INTO buildlog_data (name, project, timecreated, build_type, result)
    VALUES ('log-9.txt', 'zlib', '2023-06-05 01:00:00', 'Fuzzing', 'Finish');
CREATE INDEX ignored_idx ON buildlog_data(name);
"""


@pytest.fixture()
def db(tmp_path):
    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / "r.sqlite"))
    conn = DB(config=cfg).connect()
    yield conn
    conn.closeConnection()


def test_restore_pg_dump_copy_format(db, tmp_path):
    dump = tmp_path / "backup_clean.sql"
    dump.write_text(_PG_DUMP)
    counts = restore_sql_dump(db, str(dump))
    assert counts["buildlog_data"] == 4
    assert counts["issues"] == 2
    assert counts["total_coverage"] == 2
    assert counts["project_info"] == 2
    # projects derived from buildlog rows (the table is derived data)
    assert counts["projects"] == 4
    # NULL decoding and COPY escapes
    rows = dict(db.query(
        "SELECT name, revisions FROM buildlog_data ORDER BY name"))
    assert rows["log-3.txt"] is None
    assert rows["log-4.txt"] == "{tab\tin\tvalue,1}"
    # the unknown table's block was skipped entirely
    assert db.count("SELECT * FROM issues", ()) == 2


def test_restored_dump_feeds_the_analysis_stack(db, tmp_path):
    """End to end: restore -> columnar extraction -> RQ1 on both engines."""
    dump = tmp_path / "backup_clean.sql"
    dump.write_text(_PG_DUMP)
    restore_sql_dump(db, str(dump))
    from tse1m_tpu.backend.jax_backend import JaxBackend
    from tse1m_tpu.backend.pandas_backend import PandasBackend
    from tse1m_tpu.data.columnar import StudyArrays

    cfg = Config(engine="sqlite", sqlite_path=db.config.sqlite_path,
                 limit_date="2024-01-01", min_coverage_days=1)
    arrays = StudyArrays.from_db(db, cfg)
    limit_ns = int(np.datetime64("2024-01-01", "ns").astype(np.int64))
    a = PandasBackend().rq1_detection(arrays, limit_ns, 1)
    b = JaxBackend(mesh=None).rq1_detection(arrays, limit_ns, 1)
    np.testing.assert_array_equal(a.detected_counts, b.detected_counts)


def test_restore_insert_format(db, tmp_path):
    dump = tmp_path / "inserts.sql"
    dump.write_text(_INSERT_DUMP)
    counts = restore_sql_dump(db, str(dump))
    assert counts["project_info"] == 1
    assert counts["buildlog_data"] == 1
    assert counts["skipped_statements"] >= 2  # SET + CREATE INDEX
    assert db.count("SELECT * FROM buildlog_data", ()) == 1


def test_restore_canonicalizes_result_enum(db, tmp_path):
    """A dump produced by the reference's analyzer carries result='Success'
    (4_get_buildlog_analysis.py:230-237) where every query filters
    ('Finish','Halfway') — restore must map it like ingest does."""
    dump = tmp_path / "legacy.sql"
    dump.write_text(
        "COPY public.buildlog_data (name, project, timecreated, build_type,"
        " result) FROM stdin;\n"
        "log-a.txt\tzlib\t2023-06-01 01:00:00\tFuzzing\tSuccess\n"
        "log-b.txt\tzlib\t2023-06-01 02:00:00\tFuzzing\tError\n"
        "\\.\n")
    restore_sql_dump(db, str(dump))
    rows = dict(db.query("SELECT name, result FROM buildlog_data"))
    assert rows["log-a.txt"] == "Finish"
    assert rows["log-b.txt"] == "Error"


def test_restore_insert_edge_cases(db, tmp_path):
    """INSERT-format edge cases: multi-row VALUES lists count rows (not
    statements), literal '%'/'?' in data survive verbatim (execute_raw —
    no driver interpolation), and a ';' at a line end inside a string
    literal doesn't split the statement."""
    dump = tmp_path / "edges.sql"
    dump.write_text(
        "INSERT INTO buildlog_data (name, project, timecreated, build_type,"
        " result) VALUES\n"
        "  ('log-1.txt', 'zlib', '2023-06-01 01:00:00', 'Fuzzing',"
        " 'Finish'),\n"
        "  ('log-2.txt', 'zlib', '2023-06-01 02:00:00', 'Fuzzing',"
        " 'Finish');\n"
        "INSERT INTO issues (project, number, rts, status, crash_type)"
        " VALUES ('zlib', '7', '2023-06-01 05:00:00', 'Fixed',"
        " 'dropped 5% after fix?;\n"
        "second line');\n")
    counts = restore_sql_dump(db, str(dump))
    assert counts["buildlog_data"] == 2
    assert counts["issues"] == 1
    (ct,) = db.query("SELECT crash_type FROM issues")[0]
    assert ct == "dropped 5% after fix?;\nsecond line"


def test_cli_restore(tmp_path):
    from tse1m_tpu.cli import main

    dump = tmp_path / "backup_clean.sql"
    dump.write_text(_PG_DUMP)
    db_path = str(tmp_path / "cli.sqlite")
    assert main(["restore", str(dump), "--db", db_path]) == 0
    cfg = Config(engine="sqlite", sqlite_path=db_path)
    conn = DB(config=cfg).connect()
    assert conn.count("SELECT * FROM buildlog_data", ()) == 4
    conn.closeConnection()
