"""graftlint: rule catalog over good/bad fixtures, suppression parsing,
baseline round-trip, the repo-clean acceptance gate, and the db/ident +
atomic-write helpers the rules point at."""

from __future__ import annotations

import json
import os
import shutil

import pytest

from tse1m_tpu.lint import engine as lint_engine
from tse1m_tpu.lint.engine import Baseline, LintError, lint_paths, main
from tse1m_tpu.lint.rules import RULES

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _rule_findings(rule: str, filename: str, relpath: str | None = None):
    """Run ONE rule over a fixture, honoring suppressions; path-scoped
    rules get a spoofed repo-relative path."""
    abspath = os.path.join(FIXTURES, filename)
    src = lint_engine.load_source(abspath, relpath or filename)
    out = []
    for f in RULES[rule](src):
        f.rule = rule
        disabled = src.line_disables.get(f.line, set())
        if not (rule in src.file_disables or rule in disabled):
            out.append(f)
    return out


# -- every rule: bad fires, good is silent -----------------------------------

@pytest.mark.parametrize("rule,bad,good,spoof", [
    ("broad-except", "bad_broad_except.py", "good_broad_except.py", None),
    ("nonatomic-write", "bad_nonatomic_write.py",
     "good_nonatomic_write.py", None),
    ("sql-interp", "bad_sql_interp.py", "good_sql_interp.py", None),
    ("host-in-jit", "bad_host_in_jit.py", "good_host_in_jit.py", None),
    ("unlocked-shared-state", "bad_unlocked_state.py",
     "good_unlocked_state.py", None),
    ("retry-bypass", "bad_retry_bypass.py", "good_retry_bypass.py", None),
    ("nondeterminism", "bad_nondeterminism.py", "good_nondeterminism.py",
     "tse1m_tpu/collect/fixture.py"),
    ("watchdog-clock", "bad_watchdog_clock.py", "good_watchdog_clock.py",
     "tse1m_tpu/cluster/pipeline.py"),
    ("watchdog-clock", "bad_lease_write.py", "good_lease_write.py",
     "tse1m_tpu/cluster/store.py"),
    # Serve plane (PR 10): slo/admission name markers bind anywhere...
    ("watchdog-clock", "bad_serve_clock.py", "good_serve_clock.py",
     "tse1m_tpu/cluster/fixture.py"),
    # ...and the whole tse1m_tpu/serve/ tree is in-plane wholesale.
    ("watchdog-clock", "bad_serve_clock.py", "good_serve_clock.py",
     "tse1m_tpu/serve/fixture.py"),
    # Request handlers stay fault-transparent: error responses are fine,
    # swallowing an InjectedFault into a JSON string is not.
    ("broad-except", "bad_serve_handler.py", "good_serve_handler.py",
     None),
    # Signature computation must dispatch through the scheme registry
    # (cluster/schemes.py), never call a raw kernel family directly.
    ("scheme-parity", "bad_scheme_parity.py", "good_scheme_parity.py",
     "tse1m_tpu/serve/fixture.py"),
    # Telemetry plane: spans close via `with` (or enter_context); the
    # manual start_span escape hatch needs a finally-guaranteed .end().
    ("span-discipline", "bad_span_discipline.py",
     "good_span_discipline.py", None),
    # graftprof: sampler/profiler threads must be literal daemon=True
    # and the plane must consult the TSE1M_PROFILING kill switch —
    # bound by name markers anywhere...
    ("prof-overhead", "bad_prof_overhead.py", "good_prof_overhead.py",
     None),
    # ...and wholesale inside the profiling module itself.
    ("prof-overhead", "bad_prof_overhead.py", "good_prof_overhead.py",
     "tse1m_tpu/observability/profiling.py"),
    # The graftprof PR pulls profiling.py + regress.py into the
    # watchdog-clock plane wholesale: profile/gate timestamps must share
    # the deadline_clock axis (the serve fixtures are the in-plane pair).
    ("watchdog-clock", "bad_serve_clock.py", "good_serve_clock.py",
     "tse1m_tpu/observability/profiling.py"),
    ("watchdog-clock", "bad_serve_clock.py", "good_serve_clock.py",
     "tse1m_tpu/observability/regress.py"),
    # Sharded serve: the new router/replica modules sit in the
    # watchdog-clock plane wholesale (serve/ prefix)...
    ("watchdog-clock", "bad_serve_clock.py", "good_serve_clock.py",
     "tse1m_tpu/serve/router.py"),
    ("watchdog-clock", "bad_serve_clock.py", "good_serve_clock.py",
     "tse1m_tpu/serve/replicate.py"),
    # ...and the write-plane split is its own rule: the router is
    # stateless; a replica's store is read_only and its served view
    # advances only through refresh().
    ("serve-write-plane", "bad_router_write.py", "good_router_write.py",
     "tse1m_tpu/serve/router.py"),
    ("serve-write-plane", "bad_replica_adopt.py", "good_replica_adopt.py",
     "tse1m_tpu/serve/replicate.py"),
    # Batched scoring plane: an out-of-plane device_put still fires;
    # the good fixture routes through the blessed scorer entry point.
    ("wire-layer", "bad_wire_layer.py", "good_wire_layer.py",
     "tse1m_tpu/serve/daemon.py"),
])
def test_rule_bad_fires_good_silent(rule, bad, good, spoof):
    assert _rule_findings(rule, bad, spoof), f"{rule} missed {bad}"
    assert not _rule_findings(rule, good, spoof), f"{rule} flagged {good}"


def test_bad_broad_except_counts_each_handler():
    assert len(_rule_findings("broad-except", "bad_broad_except.py")) == 2


def test_wire_layer_path_scoped():
    spoof = "tse1m_tpu/analysis/fixture.py"
    found = _rule_findings("wire-layer", "bad_wire_layer.py", spoof)
    assert {("device_put" in f.message, "device_get" in f.message)
            for f in found} == {(True, False), (False, True)}
    # the same calls inside the blessed wire layer are legal
    assert not _rule_findings("wire-layer", "bad_wire_layer.py",
                              "tse1m_tpu/cluster/pipeline.py")


def test_wire_layer_admits_wire_v3_seats():
    # Wire v3 (entropy codec + host prefilter) extends the blessed plane
    # by exactly these two modules — and nothing else grew a pass.
    for seat in ("tse1m_tpu/cluster/entropy.py",
                 "tse1m_tpu/cluster/prefilter.py"):
        assert not _rule_findings("wire-layer", "bad_wire_layer.py", seat)
    assert _rule_findings("wire-layer", "bad_wire_layer.py",
                          "tse1m_tpu/cluster/kernels/rans.py")


def test_wire_layer_admits_scoring_plane_seat():
    # The batched scorer's double-buffered chunk staging IS the topk
    # scan's transfer path — a blessed seat; the OTHER kernels/ modules
    # stay transfer-free and keep firing.
    assert not _rule_findings("wire-layer", "bad_wire_layer.py",
                              "tse1m_tpu/cluster/kernels/score.py")
    assert _rule_findings("wire-layer", "bad_wire_layer.py",
                          "tse1m_tpu/cluster/kernels/minhash_topk.py")


def test_scheme_parity_kernel_modules_exempt():
    # The kernel-defining modules are the implementation of the plane —
    # raw calls there are the point; anywhere else they are a parity bug.
    for seat in ("tse1m_tpu/cluster/schemes.py",
                 "tse1m_tpu/cluster/minhash.py",
                 "tse1m_tpu/cluster/minhash_pallas.py",
                 "tse1m_tpu/cluster/host.py"):
        assert not _rule_findings("scheme-parity", "bad_scheme_parity.py",
                                  seat)
    found = _rule_findings("scheme-parity", "bad_scheme_parity.py",
                           "tse1m_tpu/cluster/pipeline.py")
    # one finding per raw kernel call site in the fixture
    assert len(found) == 4


def test_prof_overhead_counts_and_kill_switch():
    # two non-daemon spawns (absent flag, computed flag) + one
    # kill-switch finding for the file
    found = _rule_findings("prof-overhead", "bad_prof_overhead.py")
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 3
    assert "daemon=True" in msgs
    assert "TSE1M_PROFILING" in msgs


def test_serve_write_plane_counts_and_scope():
    # Router fixture: store handle + store mutator + writable open = 3;
    # replica fixture: writable handle + adoption assign + adoption
    # call + store mutator = 4; outside the two modules the rule is
    # silent (the writer daemon legitimately mutates its store).
    router = _rule_findings("serve-write-plane", "bad_router_write.py",
                            "tse1m_tpu/serve/router.py")
    assert len(router) == 3
    replica = _rule_findings("serve-write-plane", "bad_replica_adopt.py",
                             "tse1m_tpu/serve/replicate.py")
    assert len(replica) == 4
    msgs = " | ".join(f.message for f in router + replica)
    assert "STATELESS" in msgs and "read_only=True" in msgs
    assert "refresh()" in msgs
    for off_plane in ("tse1m_tpu/serve/daemon.py",
                      "tse1m_tpu/cluster/store.py"):
        assert not _rule_findings("serve-write-plane",
                                  "bad_router_write.py", off_plane)
        assert not _rule_findings("serve-write-plane",
                                  "bad_replica_adopt.py", off_plane)


def test_nondeterminism_scoped_to_replay_planes():
    # outside resilience/collect/db/cluster the rule stays silent
    assert not _rule_findings("nondeterminism", "bad_nondeterminism.py",
                              "tse1m_tpu/analysis/fixture.py")


def test_host_in_jit_flags_each_class():
    found = _rule_findings("host-in-jit", "bad_host_in_jit.py")
    msgs = " | ".join(f.message for f in found)
    assert "np.float32" in msgs           # host numpy in traced body
    assert "float()" in msgs              # host scalar pull
    assert ".item()" in msgs              # blocking sync
    assert "control flow" in msgs         # if on a traced param


# -- suppressions ------------------------------------------------------------

def test_suppression_same_line_and_reason(tmp_path):
    p = tmp_path / "s.py"
    p.write_text(
        "try:\n    x = 1\n"
        "except Exception:  # graftlint: disable=broad-except -- why not\n"
        "    pass\n")
    src = lint_engine.load_source(str(p), "s.py")
    assert src.line_disables == {3: {"broad-except"}}
    assert src.suppress_reasons[0]["reason"] == "why not"
    findings = lint_paths([str(p)], root=str(tmp_path))
    assert all(f.suppressed for f in findings if f.rule == "broad-except")


def test_suppression_standalone_comment_covers_next_line(tmp_path):
    p = tmp_path / "s.py"
    p.write_text(
        "import jax\n"
        "# graftlint: disable=wire-layer -- probe\n"
        "d = jax.device_put([1])\n")
    src = lint_engine.load_source(str(p), "s.py")
    assert src.line_disables == {3: {"wire-layer"}}


def test_suppression_file_level(tmp_path):
    p = tmp_path / "s.py"
    p.write_text(
        "# graftlint: disable-file=broad-except -- fixture file\n"
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
        "try:\n    y = 2\nexcept Exception:\n    pass\n")
    findings = lint_paths([str(p)], root=str(tmp_path))
    broad = [f for f in findings if f.rule == "broad-except"]
    assert len(broad) == 2 and all(f.suppressed for f in broad)


# -- baseline round-trip -----------------------------------------------------

def test_baseline_roundtrip_and_regression(tmp_path):
    work = tmp_path / "repo"
    work.mkdir()
    target = work / "mod.py"
    shutil.copy(os.path.join(FIXTURES, "bad_broad_except.py"), target)
    bl_path = str(tmp_path / "baseline.json")

    # 1. no baseline: findings fire
    findings = lint_paths([str(target)], root=str(work))
    live = [f for f in findings if not f.suppressed]
    assert live

    # 2. write the baseline, findings absorb
    Baseline.write(bl_path, findings)
    baseline = Baseline.load(bl_path)
    entries = json.load(open(bl_path))["findings"]
    assert all(e["reason"] for e in entries)
    findings2 = lint_paths([str(target)], root=str(work),
                           baseline=baseline)
    assert all(f.baselined for f in findings2 if not f.suppressed)

    # 3. a NEW violation regresses even though the old ones are baselined
    target.write_text(target.read_text()
                      + "\n\ndef fresh(fn):\n    try:\n        fn()\n"
                        "    except Exception as boom:\n        return boom\n")
    baseline = Baseline.load(bl_path)
    findings3 = lint_paths([str(target)], root=str(work),
                           baseline=baseline)
    fresh = [f for f in findings3 if not f.suppressed and not f.baselined]
    assert len(fresh) == 1
    assert fresh[0].text.startswith("except Exception as boom")

    # 4. fixing a baselined line turns its entry stale (visible, removable)
    target.write_text("x = 1\n")
    baseline = Baseline.load(bl_path)
    assert not lint_paths([str(target)], root=str(work), baseline=baseline)
    assert baseline.stale_entries()


def test_baseline_multiplicity(tmp_path):
    """Two identical offending lines need TWO units of baseline budget —
    adding a third identical one still regresses."""
    body = ("def f(a):\n    try:\n        a()\n    except Exception:\n"
            "        pass\n")
    target = tmp_path / "m.py"
    target.write_text(body + body.replace("def f", "def g"))
    findings = lint_paths([str(target)], root=str(tmp_path))
    bl_path = str(tmp_path / "b.json")
    Baseline.write(bl_path, findings)
    target.write_text(target.read_text() + body.replace("def f", "def h"))
    baseline = Baseline.load(bl_path)
    out = lint_paths([str(target)], root=str(tmp_path), baseline=baseline)
    new = [f for f in out if not f.baselined]
    assert len(new) == 1


# -- whole-repo gate + CLI ---------------------------------------------------

def test_repo_is_lint_clean():
    """THE acceptance gate: python -m tse1m_tpu.lint exits 0 on the repo
    (every pre-existing finding fixed, suppressed with a reason, or
    baselined with a reason)."""
    from tse1m_tpu.lint import run_repo_lint

    summary = run_repo_lint()
    assert summary["ok"] is True
    assert summary["new_findings"] == 0


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad = str(tmp_path / "bad.py")
    shutil.copy(os.path.join(FIXTURES, "bad_retry_bypass.py"), bad)
    assert main([bad]) == 1
    capsys.readouterr()
    assert main([bad, "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] is False
    assert report["by_rule"]["retry-bypass"] >= 1
    assert report["findings"][0]["path"].endswith("bad.py")
    # unknown rule name is a usage error
    assert main([bad, "--rules", "no-such-rule"]) == 2
    # rule subsetting
    assert main([bad, "--rules", "broad-except"]) == 0


def test_run_repo_lint_raises_with_summary(tmp_path, monkeypatch):
    """run_repo_lint (the cli-all step) raises LintError carrying the
    machine summary when a violation is planted."""
    planted = os.path.join(lint_engine.repo_root(), "tse1m_tpu",
                           "_graftlint_planted.py")
    with open(planted, "w", encoding="utf-8") as f:
        f.write("import requests\n\n\ndef f(u):\n"
                "    return requests.get(u)\n")
    try:
        with pytest.raises(LintError) as ei:
            from tse1m_tpu.lint import run_repo_lint

            run_repo_lint()
        assert ei.value.step_result["new_findings"] == 1
        assert ei.value.step_result["by_rule"] == {"retry-bypass": 1}
    finally:
        os.remove(planted)


# -- the helpers the rules point at ------------------------------------------

def test_ident_validation():
    from tse1m_tpu.db.ident import (InvalidIdentifier, col_list,
                                    quote_ident, validate_ident)

    assert validate_ident("buildlog_data") == "buildlog_data"
    assert quote_ident("_x9") == "_x9"
    assert col_list(["a", "b_c"]) == "a, b_c"
    for bad in ("", "1abc", 'na"me', "a b", "a;drop", "a-b", "x" * 64,
                None, 42):
        with pytest.raises((InvalidIdentifier, TypeError)):
            validate_ident(bad)  # type: ignore[arg-type]


def test_restore_rejects_hostile_copy_header(tmp_path):
    """A dump whose COPY column list smuggles SQL must fail loudly at the
    identifier validator, not execute."""
    from tse1m_tpu.config import Config
    from tse1m_tpu.db.connection import DB
    from tse1m_tpu.db.ident import InvalidIdentifier
    from tse1m_tpu.db.restore import restore_sql_dump

    dump = tmp_path / "evil.sql"
    dump.write_text(
        'COPY projects (project_name); DROP TABLE issues; --) FROM stdin;\n'
        "x\n\\.\n"
        "COPY issues (project, number; DELETE FROM issues) FROM stdin;\n"
        "a\tb\n\\.\n")
    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / "db.sqlite"))
    db = DB(config=cfg).connect()
    try:
        with pytest.raises(InvalidIdentifier):
            restore_sql_dump(db, str(dump))
    finally:
        db.closeConnection()


def test_atomic_write_success_and_failure(tmp_path):
    from tse1m_tpu.utils.atomic import atomic_write

    path = str(tmp_path / "out" / "a.json")
    with atomic_write(path) as f:
        f.write('{"ok": 1}')
    assert json.load(open(path)) == {"ok": 1}
    # a failing block leaves the previous content intact and no tmp
    with pytest.raises(RuntimeError):
        with atomic_write(path) as f:
            f.write("half-")
            raise RuntimeError("crash mid-write")
    assert json.load(open(path)) == {"ok": 1}
    assert os.listdir(os.path.dirname(path)) == ["a.json"]


def test_reraise_if_fault():
    from tse1m_tpu.resilience import InjectedFault, reraise_if_fault

    reraise_if_fault(ValueError("plain"))  # no-op
    with pytest.raises(InjectedFault):
        reraise_if_fault(InjectedFault("boom"))
