"""RQ4a: corpus grouping, backend parity, G4 pre/post oracle, artifacts."""

import os

import numpy as np
import pandas as pd
import pytest

from tse1m_tpu.analysis.corpus import g4_prepost, load_corpus_groups
from tse1m_tpu.analysis.rq4a import run_rq4a
from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.config import Config
from tse1m_tpu.data.columnar import StudyArrays

LIMIT = "2026-01-01"


@pytest.fixture(scope="module")
def arrays(study_db):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT)
    return StudyArrays.from_db(study_db, cfg)


@pytest.fixture(scope="module")
def limit_ns():
    return int(np.datetime64(LIMIT, "ns").astype(np.int64))


@pytest.fixture(scope="module")
def corpus_csv(synth_study, tmp_path_factory):
    path = tmp_path_factory.mktemp("corpus") / "project_corpus_analysis.csv"
    synth_study.corpus_analysis.to_csv(path, index=False)
    return str(path)


@pytest.fixture(scope="module")
def groups(corpus_csv, arrays):
    return load_corpus_groups(corpus_csv, set(arrays.projects))


def test_grouping_matches_reference_rules(groups, synth_study, arrays):
    df = synth_study.corpus_analysis
    df = df[df["project_name"].isin(set(arrays.projects))]
    elapsed = pd.to_numeric(df["time_elapsed_seconds"], errors="coerce")
    assert groups.groups["group2"] == set(df[elapsed == 0]["project_name"])
    assert groups.groups["group4"] == set(
        df[elapsed >= 7 * 86400]["project_name"])
    # Every eligible project lands in exactly one group.
    all_assigned = set().union(*groups.groups.values())
    assert all_assigned == set(arrays.projects)
    sizes = sum(len(v) for v in groups.groups.values())
    assert sizes == len(arrays.projects)
    # G4 projects all carry a commit time.
    assert groups.groups["group4"] <= set(groups.corpus_time_ns)


def test_missing_csv_rows_default_to_g1(corpus_csv, arrays):
    df = pd.read_csv(corpus_csv)
    truncated = df[df["project_name"] != sorted(arrays.projects)[0]]
    path = corpus_csv + ".trunc.csv"
    truncated.to_csv(path, index=False)
    g = load_corpus_groups(path, set(arrays.projects))
    assert sorted(arrays.projects)[0] in g.groups["group1"]


def test_trend_backend_parity(arrays, limit_ns, groups):
    pidx = arrays.project_index()
    g1 = groups.indices("group1", pidx)
    g2 = groups.indices("group2", pidx)
    res_pd = PandasBackend().rq4a_detection_trend(arrays, limit_ns, g1, g2,
                                                  min_projects=2)
    res_jx = JaxBackend().rq4a_detection_trend(arrays, limit_ns, g1, g2,
                                               min_projects=2)
    assert res_pd.iterations.size > 50
    for f in ("iterations", "g1_total", "g1_detected", "g2_total",
              "g2_detected"):
        np.testing.assert_array_equal(getattr(res_pd, f), getattr(res_jx, f),
                                      err_msg=f)


def test_trend_oracle(arrays, limit_ns, groups, study_db):
    """Replay the reference's per-project loop (rq4a:324-346) from DB rows."""
    from collections import defaultdict

    pidx = arrays.project_index()
    g1 = groups.indices("group1", pidx)
    g2 = groups.indices("group2", pidx)
    res = PandasBackend().rq4a_detection_trend(arrays, limit_ns, g1, g2,
                                               min_projects=1)
    stats = {"g1": defaultdict(lambda: [0, set()]),
             "g2": defaultdict(lambda: [0, set()])}
    for key, idx in (("g1", g1), ("g2", g2)):
        for p in idx:
            name = arrays.projects[p]
            builds = [pd.Timestamp(r[0]) for r in study_db.query(
                "SELECT timecreated FROM buildlog_data WHERE project=? AND "
                "build_type='Fuzzing' AND timecreated<? ORDER BY timecreated",
                (name, LIMIT))]
            if not builds:
                continue
            for i in range(len(builds)):
                stats[key][i + 1][0] += 1
            issues = [pd.Timestamp(r[0]) for r in study_db.query(
                "SELECT rts FROM issues WHERE project=? AND rts<? AND status "
                "IN ('Fixed','Fixed (Verified)') ORDER BY rts",
                (name, LIMIT))]
            for rts in issues:
                k = sum(1 for b in builds if b < rts)
                if k > 0:
                    stats[key][k][1].add(name)

    for i, it in enumerate(res.iterations):
        it = int(it)
        assert res.g1_total[i] == stats["g1"][it][0]
        assert res.g1_detected[i] == len(stats["g1"][it][1])
        assert res.g2_total[i] == stats["g2"][it][0]
        assert res.g2_detected[i] == len(stats["g2"][it][1])


def test_g4_prepost_oracle(arrays, limit_ns, groups, study_db):
    """Replay the reference's fixed-N window logic (rq4a:348-412)."""
    N = 7
    pp = g4_prepost(arrays, limit_ns, groups, N)
    assert pp.detect.shape[1] == 2 * N
    assert len(pp.kept_projects) > 0

    for name in groups.groups["group4"]:
        t_corpus = pd.Timestamp(groups.corpus_time_ns[name])
        builds = [pd.Timestamp(r[0]) for r in study_db.query(
            "SELECT timecreated FROM buildlog_data WHERE project=? AND "
            "build_type='Fuzzing' AND timecreated<? ORDER BY timecreated",
            (name, LIMIT))]
        issues = [pd.Timestamp(r[0]) for r in study_db.query(
            "SELECT rts FROM issues WHERE project=? AND rts<? AND status IN "
            "('Fixed','Fixed (Verified)') ORDER BY rts", (name, LIMIT))]
        pre_idx = [i for i, b in enumerate(builds) if b < t_corpus]
        assert pp.intro_iteration[name] == len(pre_idx)
        if not pre_idx:
            assert name not in pp.kept_projects
            continue
        last = pre_idx[-1]
        if (last - (N - 1) < 0) or (last + N >= len(builds) - 1):
            assert name in pp.missing_pre
            assert name not in pp.kept_projects
            continue
        row = pp.detect[pp.kept_projects.index(name)]
        for j, s in enumerate(pp.steps):
            idx = last - (-s - 1) if s < 0 else last + s
            expect = any(builds[idx] <= r < builds[idx + 1] for r in issues)
            assert row[j] == expect, (name, s)


@pytest.mark.parametrize("backend", ["pandas", "jax_tpu", "auto"])
def test_run_rq4a_end_to_end(study_db, tmp_path, corpus_csv, backend):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 backend=backend, result_dir=str(tmp_path), limit_date=LIMIT,
                 corpus_csv=corpus_csv, min_projects_per_iteration=2)
    out = run_rq4a(cfg, db=study_db)
    df = pd.read_csv(out["trend_csv"])
    assert len(df) == out["result"].iterations.size
    assert df.columns[0] == "Iteration"
    intro = pd.read_csv(out["intro_csv"])
    assert list(intro.columns) == ["Project", "Introduction_Iteration"]
    assert (intro["Introduction_Iteration"].values
            == np.sort(intro["Introduction_Iteration"].values)).all()
    for pdf in ("rq4_g1_g2_detection_trend.pdf", "rq4_gc_detection_trend.pdf",
                "rq4_gc_bug_detection_venn.pdf"):
        assert os.path.exists(tmp_path / "rq4" / "bug" / pdf)


def test_missing_corpus_csv_fails_with_guidance(tmp_path):
    """A missing C8 output must die with the fix, not a pandas traceback
    (the reference's rq4a_bug.py:34 read_csv crash)."""
    with pytest.raises(SystemExit, match="cli synth|collect corpus"):
        load_corpus_groups(str(tmp_path / "absent.csv"), {"p"})
