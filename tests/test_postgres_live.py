"""Live-Postgres integration (round-3 verdict missing #1).

The reference runs exclusively against a real Postgres 15
(``program/__module/dbFile.py:26-38``, ``docker-compose.yml:10-20``); this
repo's Postgres dialect layer was previously covered only at SQL-text
level.  These tests run the full ingest -> columnar -> RQ pipeline over
psycopg2 against a live server and assert bit-parity with the sqlite path
on the same synthetic study — exercising exactly the surfaces only a real
server can: ``execute_values`` bulk inserts, driver-native
datetime/timestamptz rows through ``to_epoch_ns``'s mixed path, and
``TEXT[]`` array round-trips through ``parse_array``.

Gating: needs a Postgres driver (psycopg2, or the ctypes libpq driver
db/pglib.py — present wherever ``libpq.so.5`` is) AND a reachable server.
Point ``TSE1M_PG_DSN`` at one (libpq keyword form, e.g.
``host=127.0.0.1 port=5432 dbname=replication_db user=replication_user
password=replication_pass``); with the repo's docker-compose db service up,
the default matches ``program/envFile.ini``.  Skipped otherwise.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from tse1m_tpu.db import pglib

try:
    import psycopg2  # noqa: F401
except ImportError:
    psycopg2 = None
    if not pglib.available():
        pytest.skip("no Postgres driver (psycopg2 or libpq)",
                    allow_module_level=True)

from tse1m_tpu.backend.pandas_backend import PandasBackend  # noqa: E402
from tse1m_tpu.config import Config, PostgresConfig  # noqa: E402
from tse1m_tpu.data.columnar import StudyArrays  # noqa: E402
from tse1m_tpu.data.synth import SynthSpec, generate_study  # noqa: E402
from tse1m_tpu.db.connection import DB  # noqa: E402
from tse1m_tpu.db.ingest import parse_array  # noqa: E402
from tse1m_tpu.db.schema import SCHEMA_TABLES  # noqa: E402

_DEFAULT_DSN = ("host=127.0.0.1 port=5432 dbname=replication_db "
                "user=replication_user password=replication_pass")


def _pg_config() -> PostgresConfig:
    dsn = os.environ.get("TSE1M_PG_DSN", _DEFAULT_DSN)
    kv = dict(item.split("=", 1) for item in dsn.split())
    return PostgresConfig(
        database=kv.get("dbname", "replication_db"),
        user=kv.get("user", "replication_user"),
        password=kv.get("password", ""),
        host=kv.get("host", "127.0.0.1"),
        port=int(kv.get("port", 5432)),
    )


@pytest.fixture(scope="module")
def pg_db():
    pg = _pg_config()
    cfg = Config(engine="postgres", postgres=pg, limit_date="2026-01-01")
    try:
        # Probe through the connection layer itself — whichever driver it
        # resolved (psycopg2 or the ctypes libpq driver).
        db = DB(config=cfg).connect()
    except Exception as e:  # no server — the gate, not a failure
        pytest.skip(f"no live Postgres at {pg.host}:{pg.port} ({e}); "
                    "set TSE1M_PG_DSN or `docker compose up db`")
    assert db.dialect == "postgres"
    for t in SCHEMA_TABLES:  # idempotent re-runs
        db.execute(f"DROP TABLE IF EXISTS {t} CASCADE")
    db.commit()
    yield db
    db.closeConnection()


@pytest.fixture(scope="module")
def study():
    return generate_study(SynthSpec(n_projects=10, days=400, seed=21))


@pytest.fixture(scope="module")
def pg_arrays(pg_db, study):
    # to_db -> create_schema (TIMESTAMPTZ/TEXT[]/DATE DDL) + executeValues
    # (psycopg2.extras.execute_values bulk path, dbFile.py:37's mechanism).
    study.to_db(pg_db)
    cfg = Config(engine="postgres", postgres=pg_db.config.postgres,
                 limit_date="2026-01-01")
    return StudyArrays.from_db(pg_db, cfg)


@pytest.fixture(scope="module")
def sqlite_arrays(study, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("pgpar") / "study.sqlite")
    cfg = Config(engine="sqlite", sqlite_path=path, limit_date="2026-01-01")
    db = DB(config=cfg).connect()
    study.to_db(db)
    arrays = StudyArrays.from_db(db, cfg)
    db.closeConnection()
    return arrays


def test_columnar_parity_with_sqlite(pg_arrays, sqlite_arrays):
    """Driver-native timestamptz/DATE/float rows must decode to the exact
    arrays the sqlite text path produces."""
    assert pg_arrays.projects == sqlite_arrays.projects
    for table in ("fuzz", "covb", "issues", "cov"):
        a, b = getattr(pg_arrays, table), getattr(sqlite_arrays, table)
        np.testing.assert_array_equal(a.offsets, b.offsets, err_msg=table)
    np.testing.assert_array_equal(pg_arrays.fuzz.columns["time_ns"],
                                  sqlite_arrays.fuzz.columns["time_ns"])
    np.testing.assert_array_equal(pg_arrays.issues.columns["time_ns"],
                                  sqlite_arrays.issues.columns["time_ns"])
    np.testing.assert_array_equal(pg_arrays.cov.columns["date_ns"],
                                  sqlite_arrays.cov.columns["date_ns"])
    np.testing.assert_array_equal(pg_arrays.fuzz.columns["ok"],
                                  sqlite_arrays.fuzz.columns["ok"])
    for col in ("coverage", "covered", "total"):
        np.testing.assert_array_equal(pg_arrays.cov.columns[col],
                                      sqlite_arrays.cov.columns[col],
                                      err_msg=col)
    # grouphash is a factorize over raw array representations, which differ
    # by engine (TEXT[] list vs json text) — equality PATTERN must match.
    ga = pg_arrays.covb.columns["grouphash"]
    gb = sqlite_arrays.covb.columns["grouphash"]
    assert ga.shape == gb.shape
    np.testing.assert_array_equal(ga[1:] == ga[:-1], gb[1:] == gb[:-1])


def test_native_pg_extraction_carried_the_fetch(pg_arrays):
    """With a live server and the COPY-binary decoder built, the Postgres
    extraction must ride the native path (extract_native true in bench
    terms), not the pandas fallback."""
    from tse1m_tpu.native import _load_pg

    if _load_pg() is None:
        pytest.skip("native pg decoder unavailable")
    assert pg_arrays.native_decode


def test_text_array_roundtrip(pg_arrays, sqlite_arrays):
    """TEXT[] columns come back as Python lists from psycopg2 and as json
    text from sqlite; parse_array must yield identical revision sets."""
    raw_pg = pg_arrays.fuzz.columns["revisions_raw"]
    raw_sq = sqlite_arrays.fuzz.columns["revisions_raw"]
    idx = np.linspace(0, len(raw_pg) - 1, num=min(50, len(raw_pg)),
                      dtype=np.int64)
    for i in idx:
        assert parse_array(raw_pg[i]) == parse_array(raw_sq[i]), i


def test_rq1_parity_with_sqlite(pg_arrays, sqlite_arrays):
    limit_ns = int(np.datetime64("2026-01-01", "ns").astype(np.int64))
    be = PandasBackend()
    a = be.rq1_detection(pg_arrays, limit_ns, min_projects=1)
    b = be.rq1_detection(sqlite_arrays, limit_ns, min_projects=1)
    for f in ("iterations", "total_projects", "detected_counts",
              "iteration_of_issue", "link_idx"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f),
                                      err_msg=f)


def test_rq3_parity_exercises_revhash(pg_arrays, sqlite_arrays):
    """RQ3's revision-set equality goes through parse_array + rev_hash on
    BOTH engines' raw forms — the deepest array-decode consumer."""
    limit_ns = int(np.datetime64("2026-01-01", "ns").astype(np.int64))
    be = PandasBackend()
    a = be.rq3_coverage_at_detection(pg_arrays, limit_ns)
    b = be.rq3_coverage_at_detection(sqlite_arrays, limit_ns)
    np.testing.assert_array_equal(a.det_issue_idx, b.det_issue_idx)
    np.testing.assert_array_equal(a.det_diff_percent, b.det_diff_percent)
    np.testing.assert_array_equal(a.nondet_diff_percent,
                                  b.nondet_diff_percent)
