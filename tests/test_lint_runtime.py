"""Runtime sanitizer (tse1m_tpu/lint/runtime.py): the transfer guard
catches implicit host->device staging, the compile counter sees real XLA
compiles, and the cluster hot loop passes BOTH warm — zero implicit
transfers, zero steady-state compiles."""

from __future__ import annotations

import numpy as np
import pytest

from tse1m_tpu.lint.runtime import (CompileCounter, SanitizerViolation,
                                    no_implicit_transfers, sanitized,
                                    self_check)


def test_compile_counter_sees_fresh_compile():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda v: v * 3 + 1)
    x = jnp.arange(7)
    with CompileCounter() as cold:
        f(x).block_until_ready()
    assert cold.count is not None and cold.count >= 1
    with CompileCounter() as warm:
        f(x).block_until_ready()
    assert warm.count == 0
    with CompileCounter() as reshaped:
        f(jnp.arange(13)).block_until_ready()
    assert reshaped.count >= 1


def test_transfer_guard_blocks_implicit_staging():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a, s: a + s)
    x = jnp.arange(4, dtype=jnp.uint32)
    f(x, np.uint32(3))  # compile with the implicit-staging call shape
    with no_implicit_transfers() as active:
        assert active
        with pytest.raises(Exception, match="[Dd]isallow"):
            f(x, np.uint32(3))          # np scalar staged implicitly
        jax.device_put(np.arange(3))    # explicit staging stays legal


def test_sanitized_enforces_compile_budget():
    import jax
    import jax.numpy as jnp

    g = jax.jit(lambda v: v - 2)
    with pytest.raises(SanitizerViolation, match="compile budget"):
        with sanitized(compile_budget=0):
            g(jnp.arange(31)).block_until_ready()  # fresh shape: compiles
    # the report still carries what happened when no budget is set
    with sanitized() as report:
        g(jnp.arange(57)).block_until_ready()
    assert report.compile_count >= 1
    assert report.transfer_guard_active is True


def test_self_check():
    out = self_check()
    assert out["sanitizer_available"] is True
    assert out["sanitizer_compile_count"] == 0
    assert out["sanitizer_transfer_guard"] is True


@pytest.mark.parametrize("encoding", ["auto", "delta", "pack24"])
def test_cluster_hot_loop_is_sanitizer_clean(encoding):
    """THE acceptance property: a warm cluster run performs zero implicit
    host->device transfers and zero XLA compiles, for every wire
    encoding — labels unchanged under the guard."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets

    items, _ = synth_session_sets(3000, seed=3)
    params = ClusterParams(encoding=encoding, h2d_chunks=2)
    warm = cluster_sessions(items, params)  # compile + stage everything
    with sanitized(compile_budget=0) as report:
        labels = cluster_sessions(items, params)
    np.testing.assert_array_equal(labels, warm)
    assert report.compile_count == 0
    assert report.transfer_guard_active is True


def test_cluster_resumable_is_sanitizer_clean(tmp_path):
    """The checkpointed path (shard save/load included) also stays
    implicit-transfer-free."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions_resumable
    from tse1m_tpu.data.synth import synth_session_sets

    items, _ = synth_session_sets(2500, seed=5)
    params = ClusterParams(encoding="pack24", h2d_chunks=2)
    warm = cluster_sessions_resumable(items, params,
                                      checkpoint_dir=str(tmp_path / "a"))
    with sanitized(compile_budget=0):
        labels = cluster_sessions_resumable(
            items, params, checkpoint_dir=str(tmp_path / "b"))
    np.testing.assert_array_equal(labels, warm)
