"""The `auto` backend (backend/__init__ + backend/auto.AutoBackend).

`auto` must resolve to the host oracle off-TPU (the tests' CPU platform)
and, on TPU, to a per-RQ router: the device engine only for calls whose
estimated host cost exceeds a few dispatch round-trips.  BENCH_r04's
measurement is the ground truth these tests encode: over a ~110 ms
tunneled link the device wins rq2 change points and rq3 at the 1M-build
scale but loses rq1; co-located (~0.2 ms) it wins everything non-tiny.
"""

import numpy as np
import pytest

import tse1m_tpu.backend as backend_mod
from tse1m_tpu.backend import get_backend
from tse1m_tpu.backend.auto import AutoBackend
from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.config import Config, load_config


@pytest.fixture(autouse=True)
def _reset_probe_cache():
    backend_mod._auto_rtt_s = None
    yield
    backend_mod._auto_rtt_s = None


def test_auto_resolves_to_pandas_on_cpu():
    # The test platform is CPU (conftest pins it), so auto -> host oracle.
    assert isinstance(get_backend(Config(backend="auto")), PandasBackend)


def test_auto_routes_per_rq_on_tunneled_link(monkeypatch):
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(backend_mod, "_dispatch_rtt_s", lambda: 0.11)
    be = get_backend(Config(backend="auto"))
    assert isinstance(be, AutoBackend)
    # First-call priors at 1M-build-scale row counts (BENCH_r04):
    # loop-heavy RQs go to the device even at 110 ms RTT; vectorized ones
    # stay on host.
    assert be._pick("rq2cp", 713_000)[0] == "jax"
    assert be._pick("rq3", 1_140_000)[0] == "jax"
    assert be._pick("rq1", 1_000_000)[0] == "pandas"
    assert be._pick("rq4a", 1_000_000)[0] == "pandas"
    # Small-study rows: everything stays on host.
    for key in ("rq1", "rq2cp", "rq2tr", "rq3", "rq4a", "rq4b"):
        assert be._pick(key, 20_000)[0] == "pandas"
    assert isinstance(be._pick("rq2cp", 713_000)[1], JaxBackend)
    assert isinstance(be._pick("rq1", 1_000_000)[1], PandasBackend)


def test_auto_routes_everything_to_device_when_local(monkeypatch):
    be = AutoBackend(rtt_s=0.0002)  # co-located TPU VM
    for key, rows in (("rq1", 1_000_000), ("rq2cp", 713_000),
                      ("rq2tr", 415_000), ("rq3", 1_140_000),
                      ("rq4a", 1_000_000), ("rq4b", 415_000)):
        assert be._pick(key, rows)[0] == "jax", key


def test_slow_host_measurement_flips_routing():
    """The round-4 verdict's ask: routing must derive from measurements on
    the running machine.  A measured-slow host flips the next call to the
    device even where the bootstrap prior said host."""
    be = AutoBackend(rtt_s=0.11)
    assert be._pick("rq1", 100_000)[0] == "pandas"  # prior: host wins
    be._observe("rq1", "pandas", 100_000, wall_s=5.0)  # this host is slow
    assert be._pick("rq1", 100_000)[0] == "jax"


def test_slow_device_measurement_flips_back():
    be = AutoBackend(rtt_s=0.0002)
    assert be._pick("rq2cp", 713_000)[0] == "jax"
    be._observe("rq2cp", "jax", 713_000, wall_s=30.0)  # congested device
    assert be._pick("rq2cp", 713_000)[0] == "pandas"


def test_unmeasured_engine_gets_explored():
    """The BENCH_r05 mispick (rq2tr_auto 0.345 s vs pure jax 0.138 s):
    once the host was measured, the device's bootstrap prior could never
    win the argmin, so it was never tried.  An unmeasured engine whose
    prior is within the exploration band of the measured incumbent must
    be routed once to get measured."""
    be = AutoBackend(rtt_s=0.11)
    rows = 415_000
    assert be._pick("rq2tr", rows)[0] == "pandas"      # prior: host wins
    be._observe("rq2tr", "pandas", rows, wall_s=0.31)  # the r05 host wall
    # device prior (4 RTT = 0.44 s) is inside the band: must be tried
    assert be._pick("rq2tr", rows)[0] == "jax"
    be._observe("rq2tr", "jax", rows, wall_s=0.14)     # the r05 device wall
    # both measured: measured winner sticks
    assert be._pick("rq2tr", rows)[0] == "jax"
    assert be._pick("rq2tr", rows)[0] == "jax"         # no flapping
    # hopeless priors are NOT explored (rq1-shaped: host wins 8x)
    be._observe("rq1", "pandas", 1_000_000, wall_s=0.018)
    assert be._pick("rq1", 1_000_000)[0] == "pandas"


def test_calibration_persists_across_instances(tmp_path):
    """Record-and-reuse: measured walls saved to cal_path seed the next
    AutoBackend on this machine, so a fresh process routes on last run's
    measurements instead of re-learning from priors."""
    path = str(tmp_path / "router_cal.json")
    be = AutoBackend(rtt_s=0.11, cal_path=path)
    be._observe("rq2tr", "pandas", 415_000, wall_s=0.31)
    be._observe("rq2tr", "jax", 415_000, wall_s=0.14)
    be2 = AutoBackend(rtt_s=0.11, cal_path=path)
    assert be2._cost[("rq2tr", "jax")] == pytest.approx(
        be._cost[("rq2tr", "jax")])
    assert be2._pick("rq2tr", 415_000)[0] == "jax"
    # a corrupt file degrades to priors, never crashes
    with open(path, "w") as f:
        f.write("{ not json")
    be3 = AutoBackend(rtt_s=0.11, cal_path=path)
    assert be3._cost == {}
    # a v1 flat-format file (no schema_version) is ignored wholesale —
    # its entries carry no timestamps, so their age is unknowable
    # (utils/calibration.py schema gate)
    import json

    with open(path, "w") as f:
        json.dump({"cost_per_row": {"rq9:cuda": 1.0, "rq1:pandas": 2e-8}},
                  f)
    be4 = AutoBackend(rtt_s=0.11, cal_path=path)
    assert be4._cost == {}
    # v2 schema: fresh entries load; unknown rqs/engines are ignored
    import time as _time

    from tse1m_tpu.utils.calibration import SCHEMA_VERSION

    now = _time.time()
    with open(path, "w") as f:
        json.dump({"schema_version": SCHEMA_VERSION,
                   "cost_per_row": {
                       "rq9:cuda": {"value": 1.0, "ts": now},
                       "rq1:pandas": {"value": 2e-8, "ts": now}}}, f)
    be5 = AutoBackend(rtt_s=0.11, cal_path=path)
    assert be5._cost == {("rq1", "pandas"): 2e-8}


def test_get_backend_passes_cal_path_from_env(monkeypatch, tmp_path):
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(backend_mod, "_dispatch_rtt_s", lambda: 0.11)
    path = str(tmp_path / "cal.json")
    monkeypatch.setenv("TSE1M_ROUTER_CAL", path)
    be = get_backend(Config(backend="auto"))
    assert be._cal_path == path
    # empty env disables persistence
    monkeypatch.setenv("TSE1M_ROUTER_CAL", "")
    backend_mod._auto_rtt_s = None
    assert get_backend(Config(backend="auto"))._cal_path is None


def test_first_device_call_excluded_from_calibration(study_cfg, study_db):
    """The first device call per RQ pays jit compilation and must not be
    recorded as that engine's steady-state cost."""
    from tse1m_tpu.data.columnar import StudyArrays

    arrays = StudyArrays.from_db(study_db, study_cfg)
    limit_ns = int(np.datetime64(study_cfg.limit_date, "ns")
                   .astype(np.int64))
    be = AutoBackend(rtt_s=1e-9)  # device always predicted to win
    be.rq1_detection(arrays, limit_ns, 1)
    assert ("rq1", "jax") not in be._cost  # compile call skipped
    be.rq1_detection(arrays, limit_ns, 1)
    assert ("rq1", "jax") in be._cost      # warm call recorded


def test_device_call_failover_to_host_oracle(study_cfg, study_db):
    """Device loss mid-run (injected at the production seat): the failed
    call re-runs on the host oracle with identical results, and after the
    failure limit the router stops picking the device at all — recorded
    as degradation events for the run manifest."""
    from tse1m_tpu.data.columnar import StudyArrays
    from tse1m_tpu.observability import pop_degradation_events
    from tse1m_tpu.resilience import FaultPlan, FaultRule

    arrays = StudyArrays.from_db(study_db, study_cfg)
    limit_ns = int(np.datetime64(study_cfg.limit_date, "ns")
                   .astype(np.int64))
    pop_degradation_events()
    plan = FaultPlan([FaultRule(site="backend.device.call", kind="raise",
                                message="injected: device lost", times=3)])
    with plan.active():
        be = AutoBackend(rtt_s=1e-9)  # device always predicted to win
        r1 = be.rq1_detection(arrays, limit_ns, 1)      # failover #1
        assert not be._device_lost
        r2 = be.rq2_trends(arrays, limit_ns)            # failover #2
        assert be._device_lost
        # Declared lost: the router no longer picks the device, so the
        # remaining rule budget never fires.
        r3 = be.rq3_coverage_at_detection(arrays, limit_ns)
    assert len(plan.fired) == 2
    oracle = PandasBackend()
    np.testing.assert_array_equal(
        r1.detected_counts,
        oracle.rq1_detection(arrays, limit_ns, 1).detected_counts)
    np.testing.assert_array_equal(
        r2.counts, oracle.rq2_trends(arrays, limit_ns).counts)
    np.testing.assert_array_equal(
        r3.det_issue_idx,
        oracle.rq3_coverage_at_detection(arrays, limit_ns).det_issue_idx)
    kinds = [e["kind"] for e in pop_degradation_events()]
    assert kinds.count("device_call_failover") == 2
    assert "device_failover" in kinds


def test_calibration_surfaces_in_manifest():
    from tse1m_tpu.utils.manifest import RunManifest

    be = AutoBackend(rtt_s=0.11)
    be._observe("rq1", "pandas", 1000, 0.01)
    m = RunManifest("rq1", be.name)
    m.record_backend(be)
    cal = m.extra["router_calibration"]
    assert cal["dispatch_rtt_s"] == 0.11
    assert "rq1:pandas" in cal["cost_per_row"]
    # plain engines are a no-op
    m2 = RunManifest("rq1", "pandas")
    m2.record_backend(PandasBackend())
    assert "router_calibration" not in m2.extra


def test_auto_probe_cached_per_process(monkeypatch):
    calls = []

    def probe():
        calls.append(1)
        return 0.11

    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(backend_mod, "_dispatch_rtt_s", probe)
    get_backend(Config(backend="auto"))
    get_backend(Config(backend="auto"))
    assert len(calls) == 1


def test_auto_probe_failure_falls_back_to_pandas(monkeypatch):
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def boom():
        raise RuntimeError("device held by another process")

    monkeypatch.setattr(backend_mod, "_dispatch_rtt_s", boom)
    assert isinstance(get_backend(Config(backend="auto")), PandasBackend)


def test_auto_backend_results_match_oracle(study_cfg, study_db):
    """End-to-end: routed results are identical to the host oracle no
    matter which engine served each call."""
    from tse1m_tpu.data.columnar import StudyArrays

    arrays = StudyArrays.from_db(study_db, study_cfg)
    limit_ns = int(np.datetime64(study_cfg.limit_date, "ns")
                   .astype(np.int64))
    # Force the device engine for every call (rtt ~ 0) to exercise routing
    # through the jax path on the virtual mesh.
    be = AutoBackend(rtt_s=1e-6)
    want = PandasBackend()
    a = be.rq1_detection(arrays, limit_ns, 1)
    b = want.rq1_detection(arrays, limit_ns, 1)
    np.testing.assert_array_equal(a.detected_counts, b.detected_counts)
    a2 = be.rq3_coverage_at_detection(arrays, limit_ns)
    b2 = want.rq3_coverage_at_detection(arrays, limit_ns)
    np.testing.assert_array_equal(a2.det_issue_idx, b2.det_issue_idx)


def test_config_accepts_auto(tmp_path, monkeypatch):
    ini = tmp_path / "envFile.ini"
    ini.write_text("[FRAMEWORK]\nbackend = auto\n")
    monkeypatch.delenv("TSE1M_BACKEND", raising=False)
    assert load_config(str(ini)).backend == "auto"
    ini.write_text("[FRAMEWORK]\nbackend = cuda\n")
    with pytest.raises(ValueError, match="unknown backend"):
        load_config(str(ini))
