"""The `auto` backend switch (backend/__init__.resolve_auto_backend).

`auto` must resolve to the host oracle off-TPU (the tests' CPU platform)
and to the device backend only when dispatch latency is local-class —
over a tunneled PJRT link every device call pays the network round-trip,
which no fused kernel can beat for ms-scale RQ reductions.
"""

import pytest

import tse1m_tpu.backend as backend_mod
from tse1m_tpu.backend import get_backend, resolve_auto_backend
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.config import Config, load_config


@pytest.fixture(autouse=True)
def _reset_auto_cache():
    backend_mod._auto_choice = None
    yield
    backend_mod._auto_choice = None


def test_auto_resolves_to_pandas_on_cpu():
    # The test platform is CPU (conftest pins it), so auto -> host oracle.
    assert resolve_auto_backend() == "pandas"
    assert isinstance(get_backend(Config(backend="auto")), PandasBackend)


def test_auto_picks_device_only_when_dispatch_is_local(monkeypatch):
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(backend_mod, "_dispatch_rtt_s", lambda: 0.11)
    assert resolve_auto_backend() == "pandas"
    backend_mod._auto_choice = None
    monkeypatch.setattr(backend_mod, "_dispatch_rtt_s", lambda: 0.0002)
    assert resolve_auto_backend() == "jax_tpu"


def test_auto_choice_cached_per_process(monkeypatch):
    calls = []

    def probe():
        calls.append(1)
        return 0.11

    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(backend_mod, "_dispatch_rtt_s", probe)
    resolve_auto_backend()
    resolve_auto_backend()
    assert len(calls) == 1


def test_config_accepts_auto(tmp_path, monkeypatch):
    ini = tmp_path / "envFile.ini"
    ini.write_text("[FRAMEWORK]\nbackend = auto\n")
    monkeypatch.delenv("TSE1M_BACKEND", raising=False)
    assert load_config(str(ini)).backend == "auto"
    ini.write_text("[FRAMEWORK]\nbackend = cuda\n")
    with pytest.raises(ValueError, match="unknown backend"):
        load_config(str(ini))
