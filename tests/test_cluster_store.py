"""Persistent signature store + incremental clustering (ISSUE 4 tentpole).

The acceptance property is exactness: a warm run — any mix of cached
signatures, novel rows, accreted tails — must produce labels equal
ELEMENTWISE (hence ARI == 1.0) to a cold batch run over the same input,
across encodings and quantization widths.  Plus the store mechanics:
content addressing, policy refusal, torn/evicted shard handling, and the
wire-savings contract the bench keys report.
"""

from __future__ import annotations

import os
from dataclasses import replace

import numpy as np
import pytest

from tse1m_tpu.cluster import (ClusterParams, adjusted_rand_index,
                               cluster_sessions, cluster_sessions_resumable)
from tse1m_tpu.cluster.pipeline import last_run_info
from tse1m_tpu.cluster.store import (SignatureStore, digests_fingerprint,
                                     row_digests)
from tse1m_tpu.data.synth import synth_session_sets

POLICY = {"n_hashes": 32, "seed": 0, "quant_bits": 0}


def _params(store_dir=None, **kw):
    base = dict(n_hashes=32, n_bands=4, use_pallas="never",
                sig_store=str(store_dir) if store_dir else None)
    base.update(kw)
    return ClusterParams(**base)


# -- content digests ---------------------------------------------------------

def test_row_digests_deterministic_and_distinct():
    rng = np.random.default_rng(0)
    items = rng.integers(0, 1 << 24, size=(5000, 16), dtype=np.uint32)
    d1, d2 = row_digests(items), row_digests(items.copy())
    np.testing.assert_array_equal(d1, d2)
    # distinct rows -> distinct 128-bit digests (overwhelmingly)
    assert len({bytes(r) for r in d1}) == 5000
    # equal rows -> equal digests regardless of position
    dup = items.copy()
    dup[7] = dup[0]
    dd = row_digests(dup)
    np.testing.assert_array_equal(dd[7], dd[0])
    # single-element change flips the digest
    mod = items.copy()
    mod[3, 5] ^= 1
    assert bytes(row_digests(mod)[3]) != bytes(d1[3])


def test_row_digests_width_sensitive():
    a = np.zeros((1, 8), np.uint32)
    b = np.zeros((1, 16), np.uint32)
    assert bytes(row_digests(a)[0]) != bytes(row_digests(b)[0])


# -- store mechanics ---------------------------------------------------------

def test_store_probe_append_dedupe(tmp_path):
    store = SignatureStore(str(tmp_path), POLICY)
    rng = np.random.default_rng(1)
    items = rng.integers(0, 1 << 24, size=(100, 16), dtype=np.uint32)
    d = row_digests(items)
    sig = rng.integers(0, 1 << 32, size=(100, 32), dtype=np.uint32)
    hit, _, _ = store.bulk_probe(d)
    assert not hit.any()
    assert store.append(d, sig) == 100
    # duplicate append is a no-op; intra-batch duplicates keep the first
    assert store.append(d, sig) == 0
    dup_d = np.concatenate([d[:2], d[:2]])
    dup_s = np.concatenate([sig[:2], sig[:2]])
    assert store.append(dup_d, dup_s) == 0
    hit, sh, rw = store.bulk_probe(d)
    assert hit.all()
    got = store.load_signatures(sh, rw)
    np.testing.assert_array_equal(got, sig)
    # reopened store sees the same rows (manifest-committed)
    store2 = SignatureStore(str(tmp_path), POLICY)
    assert store2.n_rows == 100
    hit, sh, rw = store2.bulk_probe(d[::3])
    np.testing.assert_array_equal(store2.load_signatures(sh, rw), sig[::3])


def test_store_policy_mismatch_refuses(tmp_path):
    SignatureStore(str(tmp_path), POLICY)
    with pytest.raises(ValueError, match="different policy"):
        SignatureStore(str(tmp_path), {**POLICY, "n_hashes": 64})
    with pytest.raises(ValueError, match="quant_bits"):
        SignatureStore(str(tmp_path), {**POLICY, "quant_bits": 10})


def test_store_torn_shard_reads_as_absent(tmp_path):
    store = SignatureStore(str(tmp_path), POLICY)
    rng = np.random.default_rng(2)
    items = rng.integers(0, 1 << 24, size=(50, 16), dtype=np.uint32)
    d = row_digests(items)
    store.append(d, rng.integers(0, 9, size=(50, 32), dtype=np.uint32))
    shard = os.path.join(str(tmp_path), "sig_00000.npy")
    with open(shard, "rb+") as f:
        f.truncate(os.path.getsize(shard) // 2)
    store2 = SignatureStore(str(tmp_path), POLICY)
    assert store2.n_rows == 0
    hit, _, _ = store2.bulk_probe(d)
    assert not hit.any()


def test_store_eviction_fifo_and_state_invalidation(tmp_path):
    # each shard: 10 rows x 32 hashes x 4 B = 1280 B; cap at 2.5 shards
    store = SignatureStore(str(tmp_path), POLICY, max_bytes=3200)
    rng = np.random.default_rng(3)
    batches = []
    for i in range(3):
        items = rng.integers(0, 1 << 24, size=(10, 16), dtype=np.uint32)
        d = row_digests(items)
        batches.append(d)
        store.append(d, rng.integers(0, 9, size=(10, 32), dtype=np.uint32))
    # oldest shard evicted; newest two remain
    assert len(store.shards) == 2
    assert not store.bulk_probe(batches[0])[0].any()
    assert store.bulk_probe(batches[2])[0].all()
    # a state whose locator references the evicted shard reads as unusable
    labels = np.zeros(10, np.int32)
    locator = np.zeros((10, 2), np.int32)  # shard 0 = evicted
    tables = ([np.zeros(0, np.uint32)] * 4, [np.zeros(0, np.int32)] * 4)
    assert store.save_state(labels, locator, tables, batches[0], 4, 0.5)
    assert store.load_state(4, 0.5) is None


def test_store_state_roundtrip_and_mismatch(tmp_path):
    store = SignatureStore(str(tmp_path), POLICY)
    rng = np.random.default_rng(4)
    items = rng.integers(0, 1 << 24, size=(20, 16), dtype=np.uint32)
    d = row_digests(items)
    sig = rng.integers(0, 9, size=(20, 32), dtype=np.uint32)
    store.append(d, sig)
    _, sh, rw = store.bulk_probe(d)
    labels = np.arange(20, dtype=np.int32)
    keys = rng.integers(0, 99, size=(20, 4), dtype=np.uint32)
    from tse1m_tpu.cluster.incremental import build_band_tables

    tables = build_band_tables(keys)
    assert store.save_state(labels, np.stack([sh, rw], 1), tables, d, 4, 0.5)
    st = store.load_state(4, 0.5)
    assert st is not None and st.n_rows == 20
    np.testing.assert_array_equal(st.labels, labels)
    assert st.matches_prefix(d)
    assert not st.matches_prefix(d[::-1].copy())
    # banding/threshold mismatch -> no merge shortcut, but no refusal
    assert store.load_state(8, 0.5) is None
    assert store.load_state(4, 0.6) is None


def test_digests_fingerprint_order_sensitive():
    d = np.arange(8, dtype=np.uint64).reshape(4, 2)
    assert digests_fingerprint(d) != digests_fingerprint(d[::-1].copy())


# -- label parity: warm == cold ----------------------------------------------

def test_union_path_matches_cold_on_shuffled_corpus(tmp_path):
    """100% signature hits but a reordered corpus: the union path must
    reuse every cached signature and still label identically to cold."""
    items, _ = synth_session_sets(1200, set_size=16, seed=5)
    sp = _params(tmp_path / "s")
    cluster_sessions(items, sp)  # populate
    perm = np.random.default_rng(7).permutation(items.shape[0])
    shuffled = items[perm]
    warm = cluster_sessions(shuffled, sp)
    info = dict(last_run_info)
    assert info["cache_mode"] == "union"
    assert info["cache_hit_rate"] > 0.95  # intra-corpus dups collapse a few
    cold = cluster_sessions(shuffled, _params())
    np.testing.assert_array_equal(warm, cold)


def test_merge_bridges_old_components(tmp_path):
    """A novel row whose set straddles two previously-separate clusters
    must merge them — the union-find min-label semantics of the cold run,
    including relabeling the larger old component.  Fixture (seed-pinned,
    fully deterministic): clusters A and B share 6 of 16 ids (Jaccard
    0.23, below threshold — separate), the bridge carries the shared core
    plus half of each side (Jaccard ~0.52 to both); 2-row bands (32
    hashes / 16 bands) make the bucket collisions actually fire."""
    rng = np.random.default_rng(5)
    common = rng.integers(0, 1 << 24, size=6, dtype=np.uint32)
    ua = rng.integers(0, 1 << 24, size=10, dtype=np.uint32)
    ub = rng.integers(0, 1 << 24, size=10, dtype=np.uint32)
    a = np.concatenate([common, ua])
    b = np.concatenate([common, ub])
    base = np.concatenate([np.tile(a, (6, 1)), np.tile(b, (6, 1))])
    bridge = np.concatenate([common, ua[:5], ub[:5]])[None, :]
    union = np.concatenate([base, bridge])
    # 1 novel row over a 13-row corpus: raise the merge ceiling so the
    # tiny fixture still exercises the merge path
    kw = dict(n_bands=16, merge_max_novel=0.2)
    sp = _params(tmp_path / "s", **kw)
    cluster_sessions(base, sp)  # populate: two components
    assert len(set(cluster_sessions(base, _params(n_bands=16))
                   .tolist())) == 2
    warm = cluster_sessions(union, sp)
    assert dict(last_run_info)["cache_mode"] == "merge"
    cold = cluster_sessions(union, _params(n_bands=16))
    np.testing.assert_array_equal(warm, cold)
    assert len(set(cold.tolist())) == 1  # genuinely bridged


@pytest.mark.parametrize("encoding", ["auto", "delta", "pack24"])
@pytest.mark.parametrize("quant_bits", [0, -1, 8, 12])
def test_warm_labels_equal_cold_across_encodings(tmp_path, encoding,
                                                 quant_bits):
    """The ISSUE acceptance grid: warm (K novel rows over a cached base)
    labels are elementwise-identical to a cold batch run — ARI == 1.0 —
    for every encoding x quantization combination."""
    items, _ = synth_session_sets(600, set_size=16, seed=6)
    novel, _ = synth_session_sets(25, set_size=16, seed=606)
    union = np.concatenate([items, novel])
    kw = dict(encoding=encoding, wire_quant_bits=quant_bits)
    sp = _params(tmp_path / f"s_{encoding}_{quant_bits}", **kw)
    cluster_sessions(items, sp)                       # populate
    warm = cluster_sessions(union, sp)                # accreted warm run
    assert dict(last_run_info)["cache_mode"] == "merge"
    cold = cluster_sessions(union, _params(**kw))     # cold batch oracle
    np.testing.assert_array_equal(warm, cold)
    assert adjusted_rand_index(warm, cold) == 1.0


def test_warm_run_ships_a_fraction_of_cold_wire(tmp_path):
    """The wire contract behind `cache_wire_saved_mb`: a ≤1%-novel warm
    run ships ≤10% of the cold run's bytes (here it ships ONLY the novel
    tail, a ~1% sliver)."""
    items, _ = synth_session_sets(4000, set_size=16, seed=8)
    novel, _ = synth_session_sets(40, set_size=16, seed=808)
    union = np.concatenate([items, novel])
    cluster_sessions(union, _params())
    cold_bytes = last_run_info["wire_bytes"]
    sp = _params(tmp_path / "s")
    cluster_sessions(items, sp)
    warm = cluster_sessions(union, sp)
    info = dict(last_run_info)
    assert info["cache_mode"] == "merge"
    assert info["wire_bytes"] <= 0.1 * cold_bytes
    np.testing.assert_array_equal(warm, cluster_sessions(union, _params()))


def test_resumable_populates_and_warm_merges(tmp_path):
    """cluster_sessions_resumable integration: a chunk-checkpointed cold
    run populates the store; the next resumable call warm-merges without
    touching the chunked pipeline."""
    items, _ = synth_session_sets(2048, set_size=16, seed=9)
    cold = cluster_sessions(items, _params(h2d_chunks=4))
    sp = _params(tmp_path / "s", h2d_chunks=4)
    lab = cluster_sessions_resumable(items, sp,
                                     checkpoint_dir=str(tmp_path / "ck"))
    np.testing.assert_array_equal(lab, cold)
    assert dict(last_run_info)["cache_mode"] == "populate"
    lab2 = cluster_sessions_resumable(items, sp,
                                      checkpoint_dir=str(tmp_path / "ck2"))
    np.testing.assert_array_equal(lab2, cold)
    assert dict(last_run_info)["cache_mode"] == "merge"
    # the merge path never created chunk shards
    assert not os.path.exists(str(tmp_path / "ck2"))


def test_all_hit_warm_run_is_device_free(tmp_path):
    """Re-clustering the identical corpus: zero new rows, zero wire,
    labels straight from the merged state."""
    items, _ = synth_session_sets(800, set_size=16, seed=10)
    sp = _params(tmp_path / "s")
    first = cluster_sessions(items, sp)
    again = cluster_sessions(items, sp)
    info = dict(last_run_info)
    assert info["cache_mode"] == "merge"
    assert info["cache_hit_rate"] == 1.0
    assert info["cache_novel_rows"] == 0
    assert info["wire_bytes"] == 0
    np.testing.assert_array_equal(again, first)


def test_store_stats_surface_in_last_run_info(tmp_path):
    items, _ = synth_session_sets(500, set_size=16, seed=12)
    sp = _params(tmp_path / "s")
    cluster_sessions(items, sp)
    info = dict(last_run_info)
    assert info["encoding"] == "store"
    for key in ("cache_hit_rate", "cache_mode", "cache_novel_rows",
                "cache_store_rows", "wire_mb", "wire_bytes", "stages"):
        assert key in info, key
    # probe stage is part of the telemetry contract
    assert "stage_probe_s" in info["stages"]


# -- hypothesis property test ------------------------------------------------

try:
    from hypothesis import given, settings, strategies as st
    _HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - exercised only without extras
    _HAVE_HYPOTHESIS = False


if _HAVE_HYPOTHESIS:
    @settings(max_examples=8, deadline=None)
    @given(st.sampled_from(["auto", "delta", "pack24"]),
           st.sampled_from([0, 8, 12]),
           st.integers(min_value=1, max_value=40),
           st.integers(min_value=0, max_value=3))
    def test_property_incremental_ari_is_one(tmp_path_factory, encoding,
                                             quant_bits, k_novel, seed):
        """Property (ISSUE 4): for random (encoding, quant, K, seed), a
        warm run with K novel rows labels the union identically to a
        cold batch run (ARI == 1.0)."""
        d = tmp_path_factory.mktemp("sigstore")
        items, _ = synth_session_sets(300, set_size=16, seed=seed)
        novel, _ = synth_session_sets(k_novel, set_size=16, seed=1000 + seed)
        union = np.concatenate([items, novel])
        kw = dict(encoding=encoding, wire_quant_bits=quant_bits)
        sp = _params(d, **kw)
        cluster_sessions(items, sp)
        warm = cluster_sessions(union, sp)
        cold = cluster_sessions(union, _params(**kw))
        np.testing.assert_array_equal(warm, cold)
        assert adjusted_rand_index(warm, cold) == 1.0
