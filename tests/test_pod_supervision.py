"""Pod-scale supervision units (resilience/coordinator.py,
observability/merge.py, cluster/store.ShardedSignatureStore and the pod
routing seams) — everything here is in-process and fast; the real
2-process runs live in tests/test_pod_chaos.py (slow) and the CI
fault-matrix ``hostloss`` / ``heartbeat-timeout`` seats."""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from tse1m_tpu.cluster.store import (ShardedSignatureStore, SignatureStore,
                                     digest_range_ids, file_crc,
                                     row_digests)
from tse1m_tpu.observability.merge import (fragment_manifest_path,
                                           merge_run_manifests,
                                           sweep_stale_fragments)
from tse1m_tpu.resilience.coordinator import (HeartbeatWriter,
                                              HostLostError, PeerMonitor,
                                              PodSupervisor, exchange_dir,
                                              heartbeat_path,
                                              negotiate_run_nonce,
                                              resume_heartbeats,
                                              suspend_heartbeats)

POLICY = {"n_hashes": 32, "seed": 13, "quant_bits": 0}


# -- heartbeats / peer monitor ----------------------------------------------


def test_heartbeat_writer_beats_monotonic_seq(tmp_path):
    w = HeartbeatWriter(str(tmp_path), 3, interval_s=0.05)
    assert w.beat_once() == 1
    assert w.beat_once() == 2
    with open(heartbeat_path(str(tmp_path), 3)) as f:
        d = json.load(f)
    assert d["seq"] == 2 and d["process_id"] == 3 and d["run"]


def test_monitor_declares_silent_peer_lost_and_latches(tmp_path):
    w = HeartbeatWriter(str(tmp_path), 1, interval_s=0.05)
    w.beat_once()
    mon = PeerMonitor(str(tmp_path), n_processes=2, process_id=0,
                      timeout_s=0.3)
    assert mon.poll() == []  # beat observed, grace running
    time.sleep(0.45)
    assert mon.poll() == [1]
    with pytest.raises(HostLostError) as ei:
        mon.check(site="unit")
    assert "declared lost" in str(ei.value) and ei.value.lost == [1]
    # Latched: a resumed beat cannot readmit the host this run — its
    # digest range was already reassigned.
    w.beat_once()
    w.beat_once()
    assert mon.poll() == [1]


def test_monitor_live_peer_never_declared(tmp_path):
    w = HeartbeatWriter(str(tmp_path), 1, interval_s=0.05).start()
    try:
        mon = PeerMonitor(str(tmp_path), n_processes=2, process_id=0,
                          timeout_s=0.4)
        for _ in range(4):
            time.sleep(0.15)
            assert mon.poll() == []
    finally:
        w.stop()


def test_monitor_run_nonce_change_counts_as_advance(tmp_path):
    # A restarted peer begins a fresh run at seq 1; the LOWER seq with a
    # new nonce must still read as alive.
    HeartbeatWriter(str(tmp_path), 1).beat_once()
    mon = PeerMonitor(str(tmp_path), n_processes=2, process_id=0,
                      timeout_s=0.3)
    mon.poll()
    time.sleep(0.2)
    HeartbeatWriter(str(tmp_path), 1).beat_once()  # fresh nonce, seq 1
    time.sleep(0.2)
    assert mon.poll() == []  # nonce change reset the grace window


def test_suspend_heartbeats_silences_writer(tmp_path):
    w = HeartbeatWriter(str(tmp_path), 0, interval_s=0.02).start()
    try:
        suspend_heartbeats()
        time.sleep(0.1)
        with open(heartbeat_path(str(tmp_path), 0)) as f:
            seq_frozen = json.load(f)["seq"]
        time.sleep(0.15)
        with open(heartbeat_path(str(tmp_path), 0)) as f:
            assert json.load(f)["seq"] == seq_frozen
    finally:
        resume_heartbeats()
        w.stop()


def test_supervisor_guarded_raises_on_lost_peer(tmp_path):
    sup = PodSupervisor(str(tmp_path), n_processes=2, process_id=0,
                        interval_s=0.05, timeout_s=0.3)
    # Peer 1 never beats: a phase that blocks forever must turn into
    # HostLostError within ~one timeout, not hang.
    hang = threading.Event()
    t0 = time.monotonic()
    with pytest.raises(HostLostError):
        sup.guarded(hang.wait, site="unit.hang")
    assert time.monotonic() - t0 < 5.0
    hang.set()
    assert sup.survivors() == [0]


def test_supervisor_guarded_passes_result_through(tmp_path):
    sup = PodSupervisor(str(tmp_path), n_processes=1, process_id=0)
    assert sup.guarded(lambda: 41 + 1, site="unit.ok") == 42


def test_supervisor_guarded_relays_fence_signal_verbatim(tmp_path):
    """A LeaseSupersededError from the guarded phase must NOT be
    reclassified as HostLostError even when the peers look dead (the
    zombie's peers finished and exited, so their heartbeats stopped):
    wrapping the fence signal would send the fenced writer down the
    failover path to re-execute — the exact double-write the epoch
    leases exist to prevent (graftlint lease-fence semantics: `raise X
    from e` converts the signal away)."""
    from tse1m_tpu.resilience.coordinator import LeaseSupersededError

    sup = PodSupervisor(str(tmp_path), n_processes=2, process_id=0,
                        interval_s=0.05, timeout_s=0.2)
    # peer 1 never beats -> the monitor would declare it lost

    def fenced():
        raise LeaseSupersededError(
            0, {"epoch": 1, "owner": 0, "nonce": "a"},
            {"epoch": 2, "owner": 1, "nonce": "b"})

    t0 = time.monotonic()
    with pytest.raises(LeaseSupersededError):
        sup.guarded(fenced, site="unit.fence")
    # verbatim relay is also immediate: no peer-death confirmation wait
    assert time.monotonic() - t0 < 2.0


# -- run nonce / exchange dir -----------------------------------------------


def test_negotiate_run_nonce_single_process_is_local(tmp_path):
    a = negotiate_run_nonce(None)
    b = negotiate_run_nonce(
        PodSupervisor(str(tmp_path), n_processes=1, process_id=0))
    assert a != b and len(a) == 16
    int(a, 16)  # hex


def test_exchange_dir_sweeps_stale_runs(tmp_path):
    pod = str(tmp_path)
    old = exchange_dir(pod, "deadbeef00000000")
    open(os.path.join(old, "novel.p000.npz"), "wb").close()
    new = exchange_dir(pod, "feedface00000000", sweep_stale=True)
    assert os.path.isdir(new) and not os.path.exists(old)
    # sweeping again with the same nonce keeps the current dir
    assert exchange_dir(pod, "feedface00000000", sweep_stale=True) == new
    assert os.path.isdir(new)


def test_fs_exchange_single_process_roundtrip(tmp_path):
    from tse1m_tpu.parallel.multihost import fs_exchange

    payload = {"digests": np.arange(6, dtype=np.uint64).reshape(3, 2),
               "miss": np.array([True, False, True])}
    out = fs_exchange(str(tmp_path), "novel", payload)
    assert len(out) == 1
    np.testing.assert_array_equal(out[0]["digests"], payload["digests"])
    np.testing.assert_array_equal(out[0]["miss"], payload["miss"])
    assert os.path.exists(os.path.join(str(tmp_path), "novel.p000.npz"))


# -- manifest merge ----------------------------------------------------------


def _fragment(ok: bool, counts: dict, steps: list) -> dict:
    return {"ok": ok, "degradation_counts": counts, "steps": steps,
            "summary": {"ok": len(steps)}, "started_at": "t",
            "wall_seconds": 1.5}


def test_merge_run_manifests_sums_counts_and_tags_steps(tmp_path):
    d = str(tmp_path)
    for pid, counts in ((0, {"chunk_halving": 1}),
                        (1, {"chunk_halving": 2, "host_lost": 1})):
        with open(fragment_manifest_path(d, pid), "w") as f:
            json.dump(_fragment(True, counts,
                                [{"step": "cluster", "status": "ok"}]), f)
    merged = merge_run_manifests(d, 2)
    assert merged["ok"] is True
    assert merged["degradation_counts"] == {"chunk_halving": 3,
                                            "host_lost": 1}
    assert [s["process"] for s in merged["steps"]] == [0, 1]
    assert merged["pod"]["n_processes"] == 2
    assert merged["pod"]["merged_from"] == [0, 1]
    assert merged["pod"]["missing"] == []
    on_disk = json.load(open(os.path.join(d, "run_manifest.json")))
    assert on_disk["degradation_counts"] == merged["degradation_counts"]


def test_merge_records_missing_fragment_and_fails_ok(tmp_path):
    d = str(tmp_path)
    with open(fragment_manifest_path(d, 0), "w") as f:
        json.dump(_fragment(True, {}, [{"step": "cluster",
                                        "status": "ok"}]), f)
    merged = merge_run_manifests(d, 2)  # fragment 1 never written
    assert merged["ok"] is False
    assert merged["pod"]["missing"] == [1]


def test_merge_any_failed_fragment_fails_pod_ok(tmp_path):
    d = str(tmp_path)
    for pid, ok in ((0, True), (1, False)):
        with open(fragment_manifest_path(d, pid), "w") as f:
            json.dump(_fragment(ok, {}, []), f)
    assert merge_run_manifests(d, 2)["ok"] is False


def test_sweep_stale_fragments(tmp_path):
    d = str(tmp_path)
    for pid in (0, 1, 2):
        open(fragment_manifest_path(d, pid), "w").write("{}")
    assert sweep_stale_fragments(d) == 3
    assert not os.path.exists(fragment_manifest_path(d, 0))


# -- digest-range sharding ---------------------------------------------------


def _items(n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(1, 2**20, size=(n, 16), dtype=np.uint32)


def test_digest_range_ids_deterministic_and_in_range():
    d = row_digests(_items(500))
    rid = digest_range_ids(d, 4)
    assert rid.shape == (500,) and rid.min() >= 0 and rid.max() < 4
    np.testing.assert_array_equal(rid, digest_range_ids(d, 4))
    # roughly uniform under the multilinear hash (no empty range at N=500)
    assert len(np.unique(rid)) == 4


def test_sharded_store_refuses_single_host_root(tmp_path):
    single = os.path.join(str(tmp_path), "single")
    SignatureStore(single, POLICY)
    with pytest.raises(ValueError) as ei:
        ShardedSignatureStore(single, POLICY, n_processes=2, process_id=0)
    assert "--sig-store" in str(ei.value)
    assert "single-host store" in str(ei.value)


def test_sharded_store_refuses_policy_mismatch(tmp_path):
    root = os.path.join(str(tmp_path), "pod")
    ShardedSignatureStore(root, POLICY)
    with pytest.raises(ValueError) as ei:
        ShardedSignatureStore(root, {**POLICY, "seed": 99})
    assert "policy" in str(ei.value)


def test_sharded_store_single_writer_per_range(tmp_path):
    root = os.path.join(str(tmp_path), "pod")
    items = _items(200)
    d = row_digests(items)
    sigs = np.arange(200 * 32, dtype=np.uint32).reshape(200, 32)
    s0 = ShardedSignatureStore(root, POLICY, n_processes=2, process_id=0)
    s1 = ShardedSignatureStore(root, POLICY, n_processes=2, process_id=1)
    assert s0.owned == [0] and s1.owned == [1]
    # each process appends only its owned range's rows
    w0 = s0.append(d, sigs)
    w1 = s1.append(d, sigs)
    assert w0 > 0 and w1 > 0 and w0 + w1 <= 200
    # every process probes EVERY range (reads are global)
    hit, loc = ShardedSignatureStore(root, POLICY, n_processes=2,
                                     process_id=0).probe(d)
    assert hit.all()
    # the non-owned range is read-only: direct append refuses
    ro = s0.range_store(1)
    assert ro.read_only
    with pytest.raises(RuntimeError) as ei:
        ro.append(d[:1], sigs[:1])
    assert "read-only" in str(ei.value)


def test_sharded_store_gather_roundtrip_and_reassignment(tmp_path):
    root = os.path.join(str(tmp_path), "pod")
    items = _items(300, seed=2)
    d = row_digests(items)
    sigs = np.arange(300 * 32, dtype=np.uint32).reshape(300, 32)
    for pid in (0, 1):
        ShardedSignatureStore(root, POLICY, n_processes=2,
                              process_id=pid).append(d, sigs)
    # survivor shape: one process inherits every range
    from tse1m_tpu.observability import pop_degradation_events

    pop_degradation_events()
    solo = ShardedSignatureStore(root, POLICY, n_processes=1, process_id=0)
    assert solo.owned == [0, 1] and solo.reassigned_ranges == [1]
    events = pop_degradation_events()
    assert any(e["kind"] == "shard_range_reassigned" for e in events)
    hit, loc = solo.probe(d)
    assert hit.all()
    np.testing.assert_array_equal(solo.load_signatures(loc), sigs)


def test_pod_row_range_partitions_exactly():
    from tse1m_tpu.parallel.multihost import pod_row_range

    for n, nproc in ((800, 2), (801, 2), (7, 3), (2, 4)):
        spans = [pod_row_range(n, nproc, p) for p in range(nproc)]
        assert spans[0][0] == 0 and spans[-1][1] == n
        for (a0, a1), (b0, b1) in zip(spans, spans[1:]):
            assert a1 == b0


# -- pod routing refusals ----------------------------------------------------


def test_cluster_sessions_mesh_plus_sig_store_refuses_loudly(tmp_path):
    """The pre-pod behavior silently DROPPED --sig-store under a mesh;
    the API-level entry point must refuse with an error naming the flag
    and the supported route."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.parallel.mesh import make_mesh

    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                           sig_store=os.path.join(str(tmp_path), "s"))
    with pytest.raises(ValueError) as ei:
        cluster_sessions(_items(64), params, mesh=make_mesh())
    msg = str(ei.value)
    assert "--sig-store" in msg and "cluster_sessions_pod" in msg


def test_cluster_sessions_pod_requires_store():
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.cluster.pipeline import cluster_sessions_pod

    with pytest.raises(ValueError) as ei:
        cluster_sessions_pod(_items(8), 8, ClusterParams())
    assert "sig_store" in str(ei.value)


# -- scrub --verify-sigs -----------------------------------------------------


def _populated_store(tmp_path, n=400):
    """A store populated through the real pod path (single process)."""
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.cluster.pipeline import cluster_sessions_pod
    from tse1m_tpu.data.synth import synth_session_sets

    items, _ = synth_session_sets(n, set_size=16, seed=13)
    root = os.path.join(str(tmp_path), "pod_store")
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                           sig_store=root)
    cluster_sessions_pod(items, n, params)
    return root, items


def test_verify_sigs_clean_store_reports_ok(tmp_path):
    root, items = _populated_store(tmp_path)
    store = ShardedSignatureStore(root, {"n_hashes": 32, "seed": 0,
                                         "quant_bits": 0})
    rep = store.verify_signatures(items, sample=64, seed=0)
    assert rep["store_scrub_verify_ok"] is True
    assert rep["store_scrub_verify_sampled"] > 0
    assert rep["store_scrub_verify_mismatch"] == 0


def test_verify_sigs_catches_pre_framing_corruption(tmp_path):
    """Flip a byte inside a committed sig shard and RESTAMP its CRC —
    the frame now vouches for corrupt bytes (the pre-framing hole) and
    only the sampled raw-row recompute can catch it."""
    root, items = _populated_store(tmp_path)
    range_dirs = [os.path.join(root, d) for d in sorted(os.listdir(root))
                  if d.startswith("range_")]
    corrupted = False
    for rd in range_dirs:
        man_path = os.path.join(rd, "store_manifest.json")
        man = json.load(open(man_path))
        if not man["shards"]:
            continue
        entry = man["shards"][0]
        sig_path = os.path.join(rd, f"sig_{entry['id']:05d}.npy")
        with open(sig_path, "r+b") as f:
            f.seek(os.path.getsize(sig_path) - 4)  # inside the data tail
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))
        entry["sig_crc"] = file_crc(sig_path)  # frame inherits the rot
        with open(man_path, "w") as f:
            json.dump(man, f)
        corrupted = True
        break
    assert corrupted, "populated store committed no shards"
    store = ShardedSignatureStore(root, {"n_hashes": 32, "seed": 0,
                                         "quant_bits": 0})
    rep = store.verify_signatures(items, sample=10_000, seed=0)
    assert rep["store_scrub_verify_ok"] is False
    assert rep["store_scrub_verify_mismatch"] >= 1
    assert rep["store_scrub_verify_quarantined"] >= 1
    # quarantined rows now probe as misses -> they recompute next run
    hit, _ = store.probe(row_digests(np.ascontiguousarray(
        items, dtype=np.uint32)))
    assert not hit.all()


def test_cli_scrub_verify_sigs_keys(tmp_path, capsys, monkeypatch):
    from tse1m_tpu.cli import main

    root, _ = _populated_store(tmp_path)
    monkeypatch.setenv("TSE1M_RESULT_DIR",
                       os.path.join(str(tmp_path), "res"))
    rc = main(["scrub", root, "--verify-sigs", "--verify-n", "400",
               "--verify-seed", "13", "--verify-set-size", "16",
               "--verify-sample", "64"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["store_scrub_verify_sampled"] > 0
    assert out["store_scrub_verify_ok"] is True
    assert out["store_scrub_ranges"] >= 1
