"""Pod-scale chaos: REAL 2-process runs through the production pod path
(tests/chaos_drivers.py ``pod`` via tests/pod_harness.py) — two workers
bring up `jax.distributed`, shard the signature store by digest range,
beat heartbeats, and exchange novel tails over the shared store root.

The headline assertion is the MapReduce-style failover contract: SIGKILL
one worker mid-run and the surviving coordinator must re-execute the lost
host's partition with its digest range reassigned, producing labels
ELEMENTWISE-EQUAL to an uninterrupted run — and the merged
run_manifest.json must say exactly what happened."""

from __future__ import annotations

import json
import os
import signal

import numpy as np
import pytest

from pod_harness import (KILL_WORKER_PLAN, cold_labels, run_single_pod,
                         spawn_pod)

N, SEED = 800, 13


@pytest.fixture(scope="module")
def cold(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("pod_cold"))
    return cold_labels(tmp, n=N, seed=SEED)


@pytest.mark.slow
def test_two_process_pod_clean_then_warm(tmp_path, cold):
    """Clean pod run == cold labels on both processes; a second run
    against the same sharded store is warm (hit rate >= the
    single-process warm value on the same corpus — the acceptance bar
    for '--sig-store is no longer dropped under a mesh')."""
    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    r1 = spawn_pod(tmp, store, os.path.join(tmp, "r1"), n=N, seed=SEED)
    for pid in (0, 1):
        assert r1[pid]["rc"] == 0, r1[pid]["err"][-3000:]
        np.testing.assert_array_equal(r1[pid]["labels"], cold)
    assert r1[0]["info"]["cache_hit_rate"] == 0.0
    assert r1[0]["info"]["pod_processes"] == 2
    assert sorted(r1[0]["info"]["pod_owned_ranges"]
                  + r1[1]["info"]["pod_owned_ranges"]) == [0, 1]

    # warm re-run over the same corpus: every row is cached pod-wide
    r2 = spawn_pod(tmp, store, os.path.join(tmp, "r2"), n=N, seed=SEED)
    for pid in (0, 1):
        assert r2[pid]["rc"] == 0, r2[pid]["err"][-3000:]
        np.testing.assert_array_equal(r2[pid]["labels"], cold)
    pod_hit = r2[0]["info"]["cache_hit_rate"]

    # single-process warm baseline on an isolated store, same corpus
    tmp_s = os.path.join(tmp, "single")
    os.makedirs(tmp_s)
    store_s = os.path.join(tmp_s, "store")
    run_single_pod(tmp_s, store_s, n=N, seed=SEED)
    s2 = run_single_pod(tmp_s, store_s, n=N, seed=SEED)
    assert s2["rc"] == 0, s2["err"][-3000:]
    assert pod_hit >= s2["info"]["cache_hit_rate"], (
        f"pod warm hit rate {pod_hit} fell below single-process "
        f"{s2['info']['cache_hit_rate']}")

    # merged manifest: both fragments folded, pod-wide ok
    m = json.load(open(os.path.join(tmp, "r2", "run_manifest.json")))
    assert m["ok"] is True
    assert m["pod"] == {"n_processes": 2, "merged_from": [0, 1],
                        "missing": []}
    assert {s["process"] for s in m["steps"]} == {0, 1}


@pytest.mark.slow
def test_sigkill_worker_failover_labels_match_uninterrupted(tmp_path,
                                                            cold):
    """SIGKILL worker 1 mid-MinHash: its heartbeats stop, process 0
    declares it lost, reassigns its digest range, re-executes solo, and
    the labels equal the uninterrupted run elementwise."""
    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    rdir = os.path.join(tmp, "r")
    res = spawn_pod(tmp, store, rdir, n=N, seed=SEED,
                    plans={1: KILL_WORKER_PLAN})
    assert res[1]["rc"] == -signal.SIGKILL, res[1]["rc"]
    assert res[0]["rc"] == 0, res[0]["err"][-4000:]
    np.testing.assert_array_equal(res[0]["labels"], cold)
    info = res[0]["info"]
    assert info["pod_survivor"] == 0 and info["pod_lost"] == [1]
    assert 1 in info["pod_reassigned_ranges"]
    # merged manifest: the loss, the reassignment and the failover are
    # all countable, and the dead host's fragment is recorded missing
    m = json.load(open(os.path.join(rdir, "run_manifest.json")))
    assert m["pod"]["missing"] == [1]
    for kind in ("host_lost", "pod_failover", "shard_range_reassigned"):
        assert m["degradation_counts"].get(kind, 0) >= 1, (kind, m)

    # the survivor's store is whole: a fresh single-process run against
    # it inherits both ranges and stays label-identical, fully warm
    r2 = run_single_pod(tmp, store, n=N, seed=SEED)
    assert r2["rc"] == 0, r2["err"][-3000:]
    np.testing.assert_array_equal(r2["labels"], cold)
    assert r2["info"]["cache_hit_rate"] == 1.0


@pytest.mark.slow
def test_leader_death_fences_pod_and_respawn_recovers(tmp_path, cold):
    """Process 0 hosts the XLA coordination service: its death fences
    EVERY worker within seconds (the client's error-poll fatal — no
    heartbeat can outrun a closed socket), so in-process failover is a
    worker-loss tool only.  The recovery contract is the scheduler's
    respawn: a fresh run against the same sharded root inherits every
    digest range and produces labels elementwise-equal to an
    uninterrupted run."""
    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    res = spawn_pod(tmp, store, os.path.join(tmp, "r"), n=N, seed=SEED,
                    plans={0: KILL_WORKER_PLAN})
    assert res[0]["rc"] == -signal.SIGKILL
    assert res[1]["rc"] != 0, "worker 1 must not report success after " \
                              "losing the coordination service"
    # scheduler respawn: single process, same (now partial) store root
    r = run_single_pod(tmp, store, n=N, seed=SEED)
    assert r["rc"] == 0, r["err"][-3000:]
    np.testing.assert_array_equal(r["labels"], cold)
    assert r["info"]["pod_n_ranges"] == 2  # sharded topology inherited
