"""Pod-scale chaos: REAL 2-process runs through the production pod path
(tests/chaos_drivers.py ``pod`` via tests/pod_harness.py) — two workers
take their pod identity from the env (jax.distributed never
initialized), shard the signature store by digest range, beat
heartbeats, hold epoch leases, and exchange novel tails over the shared
store root.

The headline assertions are the elastic-membership contracts: SIGKILL a
worker (or the LEADER) mid-run and the surviving process must advance
the membership epoch, re-execute the lost host's partition with its
digest range re-dealt (promoting itself to leader when process 0 died),
producing labels ELEMENTWISE-EQUAL to an uninterrupted run and one
merged run_manifest.json — and a zombie writer woken after reassignment
must self-fence on its superseded lease with zero appends."""

from __future__ import annotations

import json
import os
import signal

import numpy as np
import pytest

from pod_harness import (KILL_WORKER_PLAN, cold_labels, make_zombie_waker,
                         run_single_pod, spawn_pod, zombie_plan)

N, SEED = 800, 13


@pytest.fixture(scope="module")
def cold(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("pod_cold"))
    return cold_labels(tmp, n=N, seed=SEED)


@pytest.mark.slow
def test_two_process_pod_clean_then_warm(tmp_path, cold):
    """Clean pod run == cold labels on both processes; a second run
    against the same sharded store is warm (hit rate >= the
    single-process warm value on the same corpus — the acceptance bar
    for '--sig-store is no longer dropped under a mesh')."""
    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    r1 = spawn_pod(tmp, store, os.path.join(tmp, "r1"), n=N, seed=SEED,
                   expect_finish=(0, 1))
    for pid in (0, 1):
        assert r1[pid]["rc"] == 0, r1[pid]["err"][-3000:]
        np.testing.assert_array_equal(r1[pid]["labels"], cold)
    assert r1[0]["info"]["cache_hit_rate"] == 0.0
    assert r1[0]["info"]["pod_processes"] == 2
    assert sorted(r1[0]["info"]["pod_owned_ranges"]
                  + r1[1]["info"]["pod_owned_ranges"]) == [0, 1]

    # warm re-run over the same corpus: every row is cached pod-wide
    r2 = spawn_pod(tmp, store, os.path.join(tmp, "r2"), n=N, seed=SEED,
                   expect_finish=(0, 1))
    for pid in (0, 1):
        assert r2[pid]["rc"] == 0, r2[pid]["err"][-3000:]
        np.testing.assert_array_equal(r2[pid]["labels"], cold)
    pod_hit = r2[0]["info"]["cache_hit_rate"]

    # single-process warm baseline on an isolated store, same corpus
    tmp_s = os.path.join(tmp, "single")
    os.makedirs(tmp_s)
    store_s = os.path.join(tmp_s, "store")
    run_single_pod(tmp_s, store_s, n=N, seed=SEED)
    s2 = run_single_pod(tmp_s, store_s, n=N, seed=SEED)
    assert s2["rc"] == 0, s2["err"][-3000:]
    assert pod_hit >= s2["info"]["cache_hit_rate"], (
        f"pod warm hit rate {pod_hit} fell below single-process "
        f"{s2['info']['cache_hit_rate']}")

    # merged manifest: both fragments folded, pod-wide ok
    m = json.load(open(os.path.join(tmp, "r2", "run_manifest.json")))
    assert m["ok"] is True
    assert m["pod"]["n_processes"] == 2
    assert m["pod"]["merged_from"] == [0, 1]
    assert m["pod"]["missing"] == []
    assert {s["process"] for s in m["steps"]} == {0, 1}


@pytest.mark.slow
def test_sigkill_worker_failover_labels_match_uninterrupted(tmp_path,
                                                            cold):
    """SIGKILL worker 1 mid-MinHash: its heartbeats stop, process 0
    declares it lost, reassigns its digest range, re-executes solo, and
    the labels equal the uninterrupted run elementwise."""
    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    rdir = os.path.join(tmp, "r")
    res = spawn_pod(tmp, store, rdir, n=N, seed=SEED,
                    plans={1: KILL_WORKER_PLAN})
    assert res[1]["rc"] == -signal.SIGKILL, res[1]["rc"]
    assert res[0]["rc"] == 0, res[0]["err"][-4000:]
    np.testing.assert_array_equal(res[0]["labels"], cold)
    info = res[0]["info"]
    assert info["pod_survivor"] == 0 and info["pod_lost"] == [1]
    assert 1 in info["pod_reassigned_ranges"]
    # merged manifest: the loss, the reassignment and the failover are
    # all countable, and the dead host's fragment is recorded missing
    m = json.load(open(os.path.join(rdir, "run_manifest.json")))
    assert m["pod"]["missing"] == [1]
    for kind in ("host_lost", "pod_failover", "shard_range_reassigned"):
        assert m["degradation_counts"].get(kind, 0) >= 1, (kind, m)

    # the survivor's store is whole: a fresh single-process run against
    # it inherits both ranges and stays label-identical, fully warm
    r2 = run_single_pod(tmp, store, n=N, seed=SEED)
    assert r2["rc"] == 0, r2["err"][-3000:]
    np.testing.assert_array_equal(r2["labels"], cold)
    assert r2["info"]["cache_hit_rate"] == 1.0


@pytest.mark.slow
def test_leader_death_promotes_survivor_no_respawn(tmp_path, cold):
    """SIGKILL the LEADER (process 0) mid-run: the pod plane has no XLA
    coordination client to fatal the survivor, so worker 1 declares the
    loss through the heartbeat monitor, PROMOTES itself (advancing the
    membership epoch — leader death is one more reassignment), re-
    executes solo with labels elementwise-equal to an uninterrupted run,
    and writes the one merged run_manifest.json.  No respawn involved."""
    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    rdir = os.path.join(tmp, "r")
    res = spawn_pod(tmp, store, rdir, n=N, seed=SEED,
                    plans={0: KILL_WORKER_PLAN}, expect_finish=(1,))
    assert res[0]["rc"] == -signal.SIGKILL
    assert res[1]["rc"] == 0, res[1]["err"][-4000:]
    np.testing.assert_array_equal(res[1]["labels"], cold)
    info = res[1]["info"]
    assert info["pod_survivor"] == 1 and info["pod_lost"] == [0]
    assert info["pod_promoted_leader"] is True
    assert info["pod_epoch"] >= 1
    assert 0 in info["pod_reassigned_ranges"]
    # the promoted leader merged the fragments: one manifest, the dead
    # leader recorded missing, the promotion countable
    m = json.load(open(os.path.join(rdir, "run_manifest.json")))
    assert m["pod"]["missing"] == [0]
    for kind in ("host_lost", "pod_failover", "leader_promoted",
                 "epoch_advance"):
        assert m["degradation_counts"].get(kind, 0) >= 1, (kind, m)
    # a later single-process run against the same root re-admits at the
    # next epoch, fully warm and label-identical (elastic re-deal)
    r2 = run_single_pod(tmp, store, n=N, seed=SEED)
    assert r2["rc"] == 0, r2["err"][-3000:]
    np.testing.assert_array_equal(r2["labels"], cold)
    assert r2["info"]["cache_hit_rate"] == 1.0


@pytest.mark.slow
def test_zombie_writer_self_fences_on_superseded_lease(tmp_path, cold):
    """Wedge worker 1 at its first H2D put (heartbeats suspended), let
    process 0 declare it lost and fail over (epoch advance supersedes
    the zombie's range lease), then WAKE the zombie: it must self-fence
    — LeaseSupersededError at its first append, read-only demotion,
    ZERO rows appended to the superseded range — while the survivor's
    labels equal the uninterrupted run elementwise."""
    tmp = str(tmp_path)
    store = os.path.join(tmp, "store")
    rdir = os.path.join(tmp, "r")
    wake = os.path.join(tmp, "wake_zombie")
    res = spawn_pod(tmp, store, rdir, n=N, seed=SEED,
                    plans={1: zombie_plan(wake)},
                    expect_finish=(0, 1), straggler_timeout=240,
                    on_poll=make_zombie_waker(store, wake))
    assert res[0]["rc"] == 0, res[0]["err"][-4000:]
    np.testing.assert_array_equal(res[0]["labels"], cold)
    info = res[0]["info"]
    assert info["pod_survivor"] == 0 and info["pod_lost"] == [1]
    assert 1 in info["pod_reassigned_ranges"]
    # the zombie woke, found its lease superseded, and exited nonzero
    # WITHOUT writing labels (it abandoned the run at the fence)
    assert res[1]["rc"] not in (0, -signal.SIGKILL), res[1]["rc"]
    assert res[1]["labels"] is None
    # its own fragment records the fence as a degradation event
    frag = json.load(open(os.path.join(rdir, "run_manifest.p001.json")))
    assert frag["degradation_counts"].get("lease_superseded", 0) >= 1, frag
    step = frag["steps"][0]
    assert step["status"] == "failed"
    assert "LeaseSupersededError" in (step["error"] or "")
    # zero zombie appends: every committed shard in the zombie's old
    # range carries the survivor's appends only — a fresh run against
    # the store is fully warm and label-identical (nothing corrupt,
    # nothing double-written)
    r2 = run_single_pod(tmp, store, n=N, seed=SEED)
    assert r2["rc"] == 0, r2["err"][-3000:]
    np.testing.assert_array_equal(r2["labels"], cold)
    assert r2["info"]["cache_hit_rate"] == 1.0
