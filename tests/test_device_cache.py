"""Device-resident study cache (backend/jax_backend._study_cache).

Round-3 verdict: the single-device jax path re-staged ~30 MB of CSR arrays
on every RQ call (jax_backend.py then re-`jnp.asarray`ed per call), so the
device backend lost to its own host oracle by 48x at the 1M-build scale.
The fix uploads value-side arrays once per (StudyArrays, limit_date) — these
tests pin the reuse/invalidations contract.
"""

import numpy as np
import pytest

from tse1m_tpu.backend.jax_backend import JaxBackend, _study_cache
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.data.columnar import StudyArrays


@pytest.fixture(scope="module")
def arrays(study_cfg, study_db):
    return StudyArrays.from_db(study_db, study_cfg)


@pytest.fixture()
def limit_ns(study_cfg):
    return int(np.datetime64(study_cfg.limit_date, "ns").astype(np.int64))


def test_cache_reused_across_rq_calls(arrays, limit_ns):
    """Warm rq1/rq3 calls must run entirely from cached device buffers: the
    transfer guard turns ANY host->device staging (explicit device_put,
    jnp.asarray, or implicit jit-argument transfer) into an error, which is
    exactly the per-call re-upload regression this pins (round-3 verdict:
    0.75 s/call re-staging)."""
    import jax

    be = JaxBackend(mesh=None)
    be.rq1_detection(arrays, limit_ns, min_projects=1)  # cold: stages
    be.rq3_coverage_at_detection(arrays, limit_ns)
    cache = arrays._jax_dev_cache
    with jax.transfer_guard_host_to_device("disallow"):
        r1 = be.rq1_detection(arrays, limit_ns, min_projects=1)
        r3 = be.rq3_coverage_at_detection(arrays, limit_ns)
    assert arrays._jax_dev_cache is cache
    assert r1.iterations.size and r3.nondet_diff_percent.size


def test_cutoff_sweep_keeps_study_level_entries(arrays, limit_ns):
    """A new cutoff must re-derive only the masked views; the big
    cutoff-independent lanes (full fuzz times, issues) stay resident."""
    be = JaxBackend(mesh=None)
    be.rq1_detection(arrays, limit_ns, min_projects=1)
    cache = arrays._jax_dev_cache
    fuzz_entry = cache["fuzz"]
    issues_entry = cache["issues"]
    day_ns = 86_400_000_000_000
    limit2 = limit_ns - 30 * day_ns
    res2 = be.rq1_detection(arrays, limit2, min_projects=1)
    assert arrays._jax_dev_cache is cache
    assert cache["fuzz"] is fuzz_entry
    assert cache["issues"] is issues_entry
    assert f"fuzz_ok:{limit_ns}" in cache and f"fuzz_ok:{limit2}" in cache
    # and the earlier-cutoff result still matches the host oracle
    resp = PandasBackend().rq1_detection(arrays, limit2, min_projects=1)
    np.testing.assert_array_equal(res2.link_idx, resp.link_idx)
    np.testing.assert_array_equal(res2.detected_counts, resp.detected_counts)


def test_cutoff_entries_evicted_beyond_cap(arrays, limit_ns):
    """HBM stays bounded on long cutoff sweeps: only the most recent
    _MAX_CUTOFFS cutoffs keep their masked device views."""
    from tse1m_tpu.backend.jax_backend import _MAX_CUTOFFS

    be = JaxBackend(mesh=None)
    day_ns = 86_400_000_000_000
    limits = [limit_ns - k * day_ns for k in range(_MAX_CUTOFFS + 1)]
    for lim in limits:
        be.rq1_detection(arrays, lim, min_projects=1)
    cache = arrays._jax_dev_cache
    assert f"fuzz_ok:{limits[0]}" not in cache       # oldest evicted
    for lim in limits[1:]:
        assert f"fuzz_ok:{lim}" in cache             # recent resident
    assert "fuzz" in cache and "issues" in cache     # big lanes never evicted
    # evicted cutoff still computes correctly (rebuilds on demand)
    res = be.rq1_detection(arrays, limits[0], min_projects=1)
    resp = PandasBackend().rq1_detection(arrays, limits[0], min_projects=1)
    np.testing.assert_array_equal(res.detected_counts, resp.detected_counts)


def test_cache_not_shared_across_table_swap(arrays, limit_ns):
    """A shallow copy that swaps a table must not see the old cache (the
    copy shares the `_jax_dev_cache` attribute object)."""
    import copy

    from tse1m_tpu.data.columnar import Segmented

    be = JaxBackend(mesh=None)
    be.rq1_detection(arrays, limit_ns, min_projects=1)
    a = copy.copy(arrays)
    a.issues = Segmented(
        offsets=np.zeros(arrays.n_projects + 1, dtype=np.int64),
        columns={"time_ns": np.empty(0, np.int64),
                 "number": np.empty(0, object),
                 "status": np.empty(0, object),
                 "crash_type": np.empty(0, object)})
    res = be.rq1_detection(a, limit_ns, min_projects=1)
    assert res.iteration_of_issue.size == 0
    assert (res.detected_counts == 0).all()


def test_cached_results_match_pandas(arrays, limit_ns):
    """Cache warm/cold parity: every RQ result equals the host oracle when
    all six run back-to-back against one shared cache."""
    be = JaxBackend(mesh=None)
    pd_be = PandasBackend()
    g1 = np.arange(0, arrays.n_projects, 2)
    g2 = np.arange(1, arrays.n_projects, 2)

    r1j = be.rq1_detection(arrays, limit_ns, 1)
    r1p = pd_be.rq1_detection(arrays, limit_ns, 1)
    np.testing.assert_array_equal(r1j.iterations, r1p.iterations)
    np.testing.assert_array_equal(r1j.detected_counts, r1p.detected_counts)
    np.testing.assert_array_equal(r1j.link_idx, r1p.link_idx)

    r2j = be.rq2_change_points(arrays, limit_ns)
    r2p = pd_be.rq2_change_points(arrays, limit_ns)
    np.testing.assert_array_equal(r2j.end_i, r2p.end_i)
    np.testing.assert_array_equal(r2j.covered_i, r2p.covered_i)

    r3j = be.rq3_coverage_at_detection(arrays, limit_ns)
    r3p = pd_be.rq3_coverage_at_detection(arrays, limit_ns)
    np.testing.assert_array_equal(r3j.det_issue_idx, r3p.det_issue_idx)
    np.testing.assert_allclose(r3j.det_diff_percent, r3p.det_diff_percent)

    r4j = be.rq4a_detection_trend(arrays, limit_ns, g1, g2, 1)
    r4p = pd_be.rq4a_detection_trend(arrays, limit_ns, g1, g2, 1)
    np.testing.assert_array_equal(r4j.iterations, r4p.iterations)
    np.testing.assert_array_equal(r4j.g1_detected, r4p.g1_detected)
    np.testing.assert_array_equal(r4j.g2_total, r4p.g2_total)

    tj = be.rq2_trends(arrays, limit_ns)
    tp = pd_be.rq2_trends(arrays, limit_ns)
    np.testing.assert_allclose(tj.percentiles, tp.percentiles,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(tj.mean, tp.mean, rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(tj.counts, tp.counts)
