"""The reference-compatible entry points, run exactly as a reference user
would: `bash run_all_analysis.sh` / `python3 program/research_questions/
rq*.py` (reference run_all_analysis.sh:17-46).  The shims and the
orchestration script are the drop-in contract's front door and were
otherwise exercised only by hand.

One subprocess runs the full script (six steps + synth bootstrap) against a
temp study via the TSE1M_* env overrides — a few tens of seconds on the
CPU mesh, the single slowest test in the suite but the one that proves the
reference workflow end to end.
"""

from __future__ import annotations

import os
import subprocess

import pytest


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    d = tmp_path_factory.mktemp("refrun")
    e = dict(os.environ)
    e.update({
        "JAX_PLATFORMS": "cpu",
        "TSE1M_ENGINE": "sqlite",
        "TSE1M_SQLITE_PATH": str(d / "study.sqlite"),
        "TSE1M_RESULT_DIR": str(d / "result_data"),
        "TSE1M_BACKEND": "jax_tpu",
    })
    e.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    return e


@pytest.mark.slow
def test_run_all_analysis_script(env):
    proc = subprocess.run(["bash", "run_all_analysis.sh"], cwd="/root/repo",
                          env=env, capture_output=True, text=True,
                          timeout=900)
    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-3000:])
    assert "All Research Questions have been reproduced successfully!" \
        in proc.stdout
    out = env["TSE1M_RESULT_DIR"]
    for artifact in ("rq1/rq1_detection_rate_stats.csv",
                     "rq3/detected_coverage_changes.csv",
                     "rq4/bug/rq4_gc_introduction_iteration.csv"):
        assert os.path.exists(os.path.join(out, artifact)), artifact


@pytest.mark.slow
def test_single_shim_runs_standalone(env):
    """A reference user can also invoke one RQ script directly
    (run_all_analysis.sh:17 does exactly this)."""
    proc = subprocess.run(
        ["python3", "program/research_questions/rq1_detection_rate.py"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "Retained" in proc.stdout  # the reference transcript's phrasing
