"""The reference-compatible entry points, run exactly as a reference user
would: `bash run_all_analysis.sh` / `python3 program/research_questions/
rq*.py` (reference run_all_analysis.sh:17-46).  The shims and the
orchestration script are the drop-in contract's front door and were
otherwise exercised only by hand.

One subprocess runs the full script (six steps + synth bootstrap) against a
temp study via the TSE1M_* env overrides — a few tens of seconds on the
CPU mesh, the single slowest test in the suite but the one that proves the
reference workflow end to end.
"""

from __future__ import annotations

import json
import os
import subprocess

import pytest


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    d = tmp_path_factory.mktemp("refrun")
    e = dict(os.environ)
    e.update({
        "JAX_PLATFORMS": "cpu",
        "TSE1M_ENGINE": "sqlite",
        "TSE1M_SQLITE_PATH": str(d / "study.sqlite"),
        "TSE1M_RESULT_DIR": str(d / "result_data"),
        "TSE1M_BACKEND": "jax_tpu",
    })
    e.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    return e


@pytest.mark.slow
def test_run_all_analysis_script(env):
    proc = subprocess.run(["bash", "run_all_analysis.sh"], cwd="/root/repo",
                          env=env, capture_output=True, text=True,
                          timeout=900)
    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-3000:])
    assert "All Research Questions have been reproduced successfully!" \
        in proc.stdout
    out = env["TSE1M_RESULT_DIR"]
    for artifact in ("rq1/rq1_detection_rate_stats.csv",
                     "rq3/detected_coverage_changes.csv",
                     "rq4/bug/rq4_gc_introduction_iteration.csv"):
        assert os.path.exists(os.path.join(out, artifact)), artifact


@pytest.mark.slow
def test_single_shim_runs_standalone(env):
    """A reference user can also invoke one RQ script directly
    (run_all_analysis.sh:17 does exactly this)."""
    proc = subprocess.run(
        ["python3", "program/research_questions/rq1_detection_rate.py"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "Retained" in proc.stdout  # the reference transcript's phrasing


_PREP_SHIMS = {
    "1_get_projects_infos.py": "projects",
    "2_get_buildlog_metadata.py": "gcs-metadata",
    "3_get_coverage_data.py": "coverage",
    "4_get_buildlog_analysis.py": "buildlogs",
    "5_get_issue_reports.py": "issues",
    "user_corpus.py": "corpus",
}


@pytest.mark.parametrize("script", sorted(_PREP_SHIMS))
def test_preparation_shim_wires_to_collect_step(script, env):
    """Every reference preparation entry path (SURVEY §1 L1:
    1_get_projects_infos.py:55 ... user_corpus.py) exists under
    program/preparation/ and routes into tse1m_tpu.cli's collect step —
    asserted offline via the argparse usage text."""
    proc = subprocess.run(
        ["python3", f"program/preparation/{script}", "--help"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "collect" in proc.stdout and "--data-dir" in proc.stdout


def test_projects_shim_collects_offline(env, oss_fuzz_repo, tmp_path):
    """1_get_projects_infos.py end-to-end against the synthetic oss-fuzz
    checkout (no clone, no network): writes the reference's
    project_info.csv (reference 1_get_projects_infos.py:76)."""
    data = tmp_path / "csv"
    proc = subprocess.run(
        ["python3", "program/preparation/1_get_projects_infos.py",
         "--no-clone", "--repo", oss_fuzz_repo, "--data-dir", str(data)],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-1000:])
    import pandas as pd

    df = pd.read_csv(data / "project_info.csv")
    assert {"project", "first_commit_datetime", "language"} <= set(df.columns)
    assert set(df["project"]) == {"zlib", "brotli"}


def test_corpus_shim_collects_offline(env, oss_fuzz_repo, tmp_path):
    """user_corpus.py end-to-end against the fixture checkout: with no
    GITHUB_TOKEN the merge-time resolver degrades to None (reference
    user_corpus.py:337-353's token gate) and the CSV still lands."""
    data = tmp_path / "csv"
    e = dict(env)
    e.pop("GITHUB_TOKEN", None)
    proc = subprocess.run(
        ["python3", "program/preparation/user_corpus.py",
         "--repo", oss_fuzz_repo, "--data-dir", str(data)],
        cwd="/root/repo", env=e, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-1000:])
    import pandas as pd

    df = pd.read_csv(data / "project_corpus_analysis.csv")
    assert {"project_name", "is_Corpus",
            "corpus_commit_time"} <= set(df.columns)
    assert bool(df.set_index("project_name")["is_Corpus"]["brotli"])


@pytest.mark.slow
def test_bench_script_emits_driver_artifact_line(env):
    """The driver records BENCH_r{N}.json from bench.py's single JSON line;
    a crash here silently costs the round its perf artifact, so CI runs the
    whole script end-to-end at tiny scale and checks the contract keys."""
    proc = subprocess.run(
        ["python3", "bench.py", "--n", "2000", "--iters", "1",
         "--ari-sample", "500", "--extract-builds", "5000"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=900)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    line = proc.stdout.strip().splitlines()[-1]
    d = json.loads(line)
    for key in ("metric", "value", "unit", "vs_baseline", "ari_vs_planted",
                "rq1_end_to_end_s", "rq1_end_to_end_backend",
                "rq_suite_winner", "link_dispatch_rtt_ms", "transfer_s"):
        assert key in d, key
    assert d["unit"] == "s" and d["value"] > 0


@pytest.mark.slow
def test_graft_dryrun_emits_scaling_block(env):
    """The driver validates multi-chip via dryrun_multichip(n) and records
    its tail — which must stay a parseable scaling JSON line.  (The module
    fixture's 8 virtual devices suffice; the [1,2,4] curve depends only on
    the n=4 argument.)"""
    proc = subprocess.run(
        ["python3", "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(4)"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=900)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    last = proc.stdout.strip().splitlines()[-1]
    d = json.loads(last)
    assert d["scaling"]["mode"] == "weak"
    assert [c["devices"] for c in d["scaling"]["curve"]] == [1, 2, 4]
