"""graftlint v2: the whole-program layer.

Covers the four interprocedural passes over good/bad fixture
mini-projects (tests/lint_fixtures/interproc/), witness chains +
``--why``, the digest cache (hits, invalidation, warm==cold findings),
the reverse-dependency closure, and the decorated-def suppression
regression."""

from __future__ import annotations

import glob
import json
import os
import shutil

import pytest

from tse1m_tpu.lint import engine as lint_engine
from tse1m_tpu.lint.engine import lint_project, main
from tse1m_tpu.lint.graph import build_graph, content_digest

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
INTERPROC = os.path.join(FIXTURES, "interproc")


def fixture_paths(subdir: str) -> list:
    return sorted(glob.glob(os.path.join(INTERPROC, subdir, "*.py")))


def run_fixture(subdir: str, rule: str | None = None):
    """Interprocedural findings over one fixture mini-project (per-file
    rules excluded so each pass is pinned in isolation)."""
    paths = fixture_paths(subdir)
    assert paths, f"no fixture files under {subdir}"
    findings, stats, graph = lint_project(
        paths, paths, rules={}, root=FIXTURES, use_cache=False)
    out = [f for f in findings if not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# -- each pass: bad fires, good twin is silent -------------------------------

@pytest.mark.parametrize("rule,bad,good", [
    ("sql-interp", "taint_bad", "taint_good"),
    ("retry-bypass", "taint_bad", "taint_good"),
    ("lease-fence", "fence_bad", "fence_good"),
    ("lock-order", "locks_bad", "locks_good"),
    ("fault-seat-drift", "seats_bad", "seats_good"),
    ("snapshot-publish", "snapshot_bad", "snapshot_good"),
    ("atomic-swap", "swap_bad", "swap_good"),
    ("spec-conformance", "spec_bad", "spec_good"),
    ("verb-dispatch-drift", "verbs_bad", "verbs_good"),
])
def test_pass_bad_fires_good_silent(rule, bad, good):
    assert run_fixture(bad, rule), f"{rule} missed {bad}"
    assert not run_fixture(good, rule), f"{rule} flagged {good}"


def test_snapshot_publish_finding_classes():
    """The planted mutation-after-publish fixture: every mutation shape
    is caught — in-place element write, mutating method call, numpy
    in-place sink, and the interprocedural helper mutation with its
    witness chain down to the seat."""
    found = run_fixture("snapshot_bad", "snapshot-publish")
    msgs = " | ".join(f.message for f in found)
    assert "element write" in msgs
    assert "mutating call" in msgs and "sort" in msgs
    assert "numpy in-place op" in msgs and "np.minimum.at" in msgs
    chained = [f for f in found if "call(s) away" in f.message]
    assert chained, "interprocedural mutation not chased"
    assert any("patch_labels" in w and "item-writes" in w
               for w in chained[0].witness)


def test_atomic_swap_finding_classes():
    found = run_fixture("swap_bad", "atomic-swap")
    msgs = " | ".join(f.message for f in found)
    assert "in-place mutator `append()`" in msgs
    assert "aug update" in msgs
    assert "mutation through published reference" in msgs
    assert "multi-target" in msgs
    aliased = [f for f in found
               if any("aliases" in w for w in f.witness)]
    assert aliased, "alias-laundered mutation not resolved"


def test_taint_findings_anchor_and_witness():
    sql = run_fixture("taint_bad", "sql-interp")
    assert len(sql) == 1
    f = sql[0]
    # flagged where the interpolated SQL enters the chain ...
    assert f.path.endswith("taint_bad/report.py")
    assert "run_stmt" in " ".join(f.witness)
    # ... with the raw execution seat at the end of the witness chain
    assert any("raw SQL execution" in w for w in f.witness)
    raw = run_fixture("taint_bad", "retry-bypass")
    # the laundered cursor seat is flagged at the real seat (dbwrap)
    assert any(f.path.endswith("taint_bad/dbwrap.py") for f in raw)


def test_lease_fence_finding_classes():
    found = run_fixture("fence_bad", "lease-fence")
    msgs = {f.path.rsplit("/", 1)[-1]: f.message for f in found}
    assert "store.py" in msgs  # unfenced per-range append
    assert "not dominated" in msgs["store.py"]
    assert "runner.py" in msgs  # swallowed LeaseSupersededError
    assert "absorb LeaseSupersededError" in msgs["runner.py"]
    assert "members.py" in msgs  # ledger-bypassing membership write
    assert "membership" in msgs["members.py"]
    # the swallow finding's witness walks down to the raise site
    swallow = [f for f in found if f.path.endswith("runner.py")][0]
    assert any("raises LeaseSupersededError" in w for w in swallow.witness)


def test_lock_order_cycle_and_self_deadlock():
    found = run_fixture("locks_bad", "lock-order")
    msgs = " | ".join(f.message for f in found)
    assert "cycle" in msgs
    assert "re-acquired" in msgs
    # the cycle names both modules' locks
    cyc = [f for f in found if "cycle" in f.message][0]
    assert "alpha.Recorder._lock" in cyc.message
    assert "beta.Monitor._lock" in cyc.message


def test_fault_seat_drift_classes():
    found = run_fixture("seats_bad", "fault-seat-drift")
    msgs = " | ".join(f.message for f in found)
    assert "store.extra.save" in msgs       # seat without matrix entry
    assert "store.gone.save" in msgs        # dead matrix entry
    assert "meteor" in msgs                 # unknown fault kind
    missing = [f for f in found if "store.extra.save" in f.message][0]
    assert missing.path.endswith("seats_bad/prod.py")
    dead = [f for f in found if "store.gone.save" in f.message][0]
    assert dead.path.endswith("seats_bad/ci_fault_matrix.py")


def test_spec_conformance_finding_classes():
    """Every seeded conformance hole: dead fault/verb/call seats, an
    unknown seat kind, a non-literal seat, an unknown SPEC_MODELS
    binding, and an unmodeled production fault seat with its witness."""
    found = run_fixture("spec_bad", "spec-conformance")
    msgs = " | ".join(f.message for f in found)
    assert "io.missing" in msgs            # dead fault seat
    assert "verb `evict`" in msgs          # dead verb
    assert "no_such_fn" in msgs            # dead call target
    assert "unknown seat kind" in msgs
    assert "string literal" in msgs        # non-const seat kwarg
    assert "ghost" in msgs                 # SPEC_MODELS names no spec
    assert "io.unmodeled" in msgs          # fault seat absent from spec
    unmodeled = [f for f in found if "io.unmodeled" in f.message][0]
    assert unmodeled.path.endswith("spec_bad/code.py")
    assert any("fault_point" in w for w in unmodeled.witness)


def test_verb_dispatch_drift_finding_classes():
    """Both drift directions, on three surfaces: the server handles an
    undeclared verb AND dropped a declared one, the client lost a
    method, and the forwarder speaks past its alphabet."""
    found = run_fixture("verbs_bad", "verb-dispatch-drift")
    msgs = " | ".join(f.message for f in found)
    assert "handles undeclared evict" in msgs
    assert "missing query" in msgs
    assert "LocalTransport" in msgs and "status" in msgs
    server = [f for f in found if "ServeServer" in f.message][0]
    assert server.path.endswith("verbs_bad/server.py")
    assert any("SERVER_VERBS" in w for w in server.witness)


# -- --why witness chains through the CLI ------------------------------------

def test_why_prints_witness_chain(capsys):
    paths = fixture_paths("fence_bad")
    found = run_fixture("fence_bad", "lease-fence")
    target = [f for f in found if f.path.endswith("runner.py")][0]
    # main() anchors paths at the REPO root, not the fixture root
    repo_rel = f"tests/lint_fixtures/{target.path}"
    rc = main(paths + ["--no-cache", "--why",
                       f"lease-fence:{repo_rel}:{target.line}"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "lease-fence" in out
    assert "raises LeaseSupersededError" in out


@pytest.mark.parametrize("rule,subdir,expect", [
    ("sql-interp", "taint_bad", "raw SQL execution"),
    ("retry-bypass", "taint_bad", "dbwrap"),
    ("lease-fence", "fence_bad", "LeaseSupersededError"),
    ("lock-order", "locks_bad", "_lock"),
    ("fault-seat-drift", "seats_bad", "fault_point"),
    ("snapshot-publish", "snapshot_bad", "item-writes"),
    ("atomic-swap", "swap_bad", "aliases"),
    ("spec-conformance", "spec_bad", "fault_point"),
    ("verb-dispatch-drift", "verbs_bad", "SERVER_VERBS"),
])
def test_why_works_for_every_pass(capsys, rule, subdir, expect):
    """Acceptance: each seeded bad fixture is detected AND its --why
    witness chain prints through the CLI."""
    paths = fixture_paths(subdir)
    found = [f for f in run_fixture(subdir, rule) if f.witness]
    assert found
    outputs = []
    for target in found:
        repo_rel = f"tests/lint_fixtures/{target.path}"
        rc = main(paths + ["--no-cache", "--why",
                           f"{rule}:{repo_rel}:{target.line}"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert rule in out
        outputs.append(out)
    assert any(expect in out for out in outputs)


def test_why_unknown_location_errors(capsys):
    paths = fixture_paths("fence_good")
    rc = main(paths + ["--no-cache", "--why", "lease-fence:nope.py:1"])
    assert rc == 2


def test_graph_mode_prints_edges(capsys):
    paths = fixture_paths("taint_bad")
    rc = main(paths + ["--no-cache", "--graph"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["functions"] >= 3
    assert any("daily_report" in e and e.endswith("dbwrap.run_stmt")
               for e in report["edges"])


# -- digest cache: hits, invalidation, warm == cold --------------------------

def _copy_fixture(tmp_path, subdir):
    dst = tmp_path / subdir
    shutil.copytree(os.path.join(INTERPROC, subdir), dst)
    return sorted(str(p) for p in dst.glob("*.py"))


def test_digest_cache_hits_and_invalidation(tmp_path):
    paths = _copy_fixture(tmp_path, "fence_bad")
    root = str(tmp_path)
    g1 = build_graph(paths, root=root, use_cache=True)
    assert g1.cache_hits == 0
    assert len(g1.extracted) == len(paths)
    g2 = build_graph(paths, root=root, use_cache=True)
    assert g2.cache_hits == g2.cache_files == len(paths)
    assert g2.extracted == []
    # edit ONE file -> only that file re-extracts
    store = [p for p in paths if p.endswith("store.py")][0]
    with open(store, "a") as f:
        f.write("\n# touched\n")
    g3 = build_graph(paths, root=root, use_cache=True)
    assert [p.rsplit("/", 1)[-1] for p in g3.extracted] == ["store.py"]
    assert g3.cache_hits == len(paths) - 1


def test_warm_findings_equal_cold(tmp_path):
    paths = _copy_fixture(tmp_path, "fence_bad")
    root = str(tmp_path)

    def run():
        findings, _, _ = lint_project(paths, paths, rules={}, root=root,
                                      use_cache=True)
        return [(f.rule, f.path, f.line, f.message)
                for f in findings if not f.suppressed]

    cold = run()
    warm = run()  # second run: all facts from the digest cache
    assert cold and warm == cold


def test_reverse_dependency_closure(tmp_path):
    paths = _copy_fixture(tmp_path, "taint_bad")
    root = str(tmp_path)
    g = build_graph(paths, root=root, use_cache=False)
    wrap = "taint_bad/dbwrap.py"
    closure = g.reverse_closure({wrap})
    # report.py imports dbwrap.py, so editing dbwrap re-lints report too
    assert closure == {wrap, "taint_bad/report.py"}


def test_content_digest_stability():
    assert content_digest(b"x") == content_digest(b"x")
    assert content_digest(b"x") != content_digest(b"y")


# -- matrix inventory vs the real tree ---------------------------------------

def test_real_tree_fault_seats_match_matrix():
    """The acceptance gate for fault-seat-drift: the real tree's seats
    and tests/ci_fault_matrix.py's PRODUCTION_SEATS agree, and every
    matrix plan site is a declared production seat."""
    from tse1m_tpu.lint.engine import default_targets, repo_root
    from tse1m_tpu.lint.interproc import fault_seat_drift_pass

    root = repo_root()
    graph = build_graph(default_targets(root), root=root, use_cache=False)
    findings = fault_seat_drift_pass(graph)
    assert findings == [], [f.message for f in findings]

    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    import ci_fault_matrix as m

    from tse1m_tpu.resilience.faults import _KINDS
    for seat, rec in m.PRODUCTION_SEATS.items():
        assert set(rec["kinds"]) <= set(_KINDS), (seat, rec["kinds"])
        assert rec["covered_by"]
    # the matrix's own plan builder refuses undeclared sites
    with pytest.raises(AssertionError):
        m.plan_rule("store.not.a.seat", kind="kill")


def test_real_tree_publication_discipline_clean():
    """The acceptance gate for graftrace's static layer: the real tree
    passes snapshot-publish and atomic-swap with ZERO findings (no
    baseline entries, no suppressions needed) — and the passes do see
    real protected classes and publish slots, so the silence is not a
    no-op."""
    from tse1m_tpu.lint.engine import default_targets, repo_root
    from tse1m_tpu.lint.interproc import (_protected_classes,
                                          _publish_slots,
                                          atomic_swap_pass,
                                          snapshot_publish_pass)

    root = repo_root()
    graph = build_graph(default_targets(root), root=root, use_cache=False)
    protected = _protected_classes(graph)
    assert "tse1m_tpu.cluster.incremental.LiveClusterIndex" in protected
    assert "tse1m_tpu.cluster.store._IndexSnapshot" in protected
    slots = _publish_slots(graph)
    assert "_snap" in slots.get("tse1m_tpu.cluster.store.SignatureStore",
                                set())
    assert "_index" in slots.get("tse1m_tpu.serve.daemon.ServeDaemon",
                                 set())
    for pass_fn in (snapshot_publish_pass, atomic_swap_pass):
        findings = pass_fn(graph)
        assert findings == [], [(f.location(), f.message)
                                for f in findings]


def test_real_tree_spec_conformance_clean():
    """The acceptance gate for graftspec's static layer: the real tree
    passes spec-conformance and verb-dispatch-drift with ZERO findings
    and zero baseline entries — and the passes do see all three spec
    modules, all four dispatch surfaces and the serve fault seats, so
    the silence is not a no-op."""
    from tse1m_tpu.lint.engine import default_targets, repo_root
    from tse1m_tpu.lint.interproc import (_dispatch_verbs,
                                          _production_sites,
                                          _spec_modules,
                                          spec_conformance_pass,
                                          verb_dispatch_drift_pass)

    root = repo_root()
    graph = build_graph(default_targets(root), root=root, use_cache=False)
    assert set(_spec_modules(graph)) == {"ingest_ack", "lease",
                                         "replica"}
    surfaces = _dispatch_verbs(graph)
    for const in ("SERVER_VERBS", "ROUTER_VERBS", "CLIENT_VERBS",
                  "FORWARD_VERBS"):
        assert surfaces[const], f"no {const} dispatch surface resolved"
    sites, _ = _production_sites(graph)
    assert {"serve.ingest.commit", "serve.router.forward",
            "serve.replica.stream"} <= set(sites)
    for pass_fn in (spec_conformance_pass, verb_dispatch_drift_pass):
        findings = pass_fn(graph)
        assert findings == [], [(f.location(), f.message)
                                for f in findings]


# -- suppression attaches across decorated defs (ride-along bugfix) ----------

def test_suppression_covers_decorated_def():
    path = os.path.join(FIXTURES, "suppress_decorated.py")
    src = lint_engine.load_source(path, "suppress_decorated.py")
    from tse1m_tpu.lint.rules import RULES

    findings = []
    for f in RULES["wire-layer"](src):
        f.rule = "wire-layer"
        disabled = src.line_disables.get(f.line, set())
        if "wire-layer" not in disabled:
            findings.append(f)
    # the suppressed decorator's device_put is covered (multi-line
    # decorator continuation), the control one still fires
    assert len(findings) == 1
    assert src.lines[findings[0].line - 1].strip().startswith(
        "jax.device_put([2])")


def test_suppression_covers_def_line(tmp_path):
    p = tmp_path / "s.py"
    p.write_text(
        "def deco(fn):\n    return fn\n\n"
        "# graftlint: disable=broad-except -- fixture\n"
        "@deco\n"
        "def f():\n"
        "    try:\n        pass\n"
        "    except Exception:\n        pass\n")
    src = lint_engine.load_source(str(p), "s.py")
    # the disable set spread from the decorator line to the def line
    assert "broad-except" in src.line_disables.get(5, set())
    assert "broad-except" in src.line_disables.get(6, set())


# -- incremental mode --------------------------------------------------------

def test_changed_closure_with_git(tmp_path):
    import subprocess

    def git(*a):
        subprocess.run(["git", *a], cwd=tmp_path, check=True,
                       capture_output=True)

    paths = _copy_fixture(tmp_path, "taint_good")
    git("init", "-q")
    git("add", "-A")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "x")
    wrap = [p for p in paths if p.endswith("dbwrap.py")][0]
    with open(wrap, "a") as f:
        f.write("\n# edited\n")
    from tse1m_tpu.lint.engine import changed_closure

    report, info = changed_closure(str(tmp_path), "HEAD", paths)
    assert info["changed"] == ["taint_good/dbwrap.py"]
    # the closure pulls in the importer of the edited file
    assert info["closure"] == ["taint_good/dbwrap.py",
                               "taint_good/report.py"]
    assert sorted(os.path.basename(p) for p in report) == \
        ["dbwrap.py", "report.py"]
