"""Sharded serve plane (tse1m_tpu/serve/router.py + replicate.py):
digest-range shard daemons behind the stateless fan-out router, and
read replicas over CRC-framed shard streaming.

The load-bearing claims:

- the router speaks the single-daemon verbs unchanged and its
  fan-out/min-merge partition over exact-duplicate corpora equals a
  single daemon's elementwise (canonicalized);
- an injected connection drop in the lost-ack window (fault seat
  ``serve.router.forward``) is absorbed by the retried SAME request
  id: the shard's journal replays the original ack — full ack, zero
  double-absorbed rows, and the replay is visible in router status;
- a superseded (fenced) shard writer appends ZERO rows: its next
  commit observes the advanced lease epoch and latches instead of
  writing;
- a replica's staleness is exactly the writer generations it has not
  pulled, drops to 0 after stream+refresh, and its store handle is
  read-only — write-plane verbs refuse;
- the graftrace schedule explorer drives >= 200 seeded PCT schedules
  over the two NEW interleaving classes (router vs. shard writers;
  replica refresh vs. writer eviction/stream) with zero races.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from tse1m_tpu.cluster import ClusterParams
from tse1m_tpu.cluster.store import digest_range_ids, row_digests
from tse1m_tpu.resilience.coordinator import RangeLeaseGuard
from tse1m_tpu.resilience.faults import (FaultPlan, FaultRule, clear_plan,
                                         install_plan)
from tse1m_tpu.serve import (LocalTransport, ReplicationPuller, RouterServer,
                             ServeClient, ServeDaemon, ServeReplica,
                             ShardRouter, replica_staleness, stream_shards)
from tse1m_tpu.trace.explore import explore

PARAMS = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
N_SHARDS = 2


def _unique_vectors(n: int, seed: int = 5, width: int = 16) -> np.ndarray:
    """Content-distinct random coverage rows: no near-duplicates (random
    32-bit elements never collide in a band), so the only cluster
    structure is the EXACT duplicates a test plants — identical under
    single-daemon and sharded routing (same digest -> same shard)."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**32, size=(n, width),
                        dtype=np.int64).astype(np.uint32)


def _canon(labels) -> list:
    """First-occurrence canonical form: two label arrays describe the
    same partition iff their canonical forms are equal elementwise."""
    seen: dict = {}
    return [seen.setdefault(int(v), len(seen)) for v in labels]


def _start_shards(tmp_path, n_shards: int = N_SHARDS) -> dict:
    return {sid: ServeDaemon(str(tmp_path / f"range_{sid:04d}"),
                             params=PARAMS,
                             state_commit_every=1).start()
            for sid in range(n_shards)}


def _stop_shards(daemons: dict) -> None:
    for d in daemons.values():
        d.stop(commit=False)


# -- router fan-out / min-merge parity ---------------------------------------

def test_router_partition_parity_vs_single_daemon(tmp_path):
    base = _unique_vectors(40)
    items = np.concatenate([base, base[[0, 3, 7, 3]]])  # planted exact dups
    single = ServeDaemon(str(tmp_path / "single"), params=PARAMS).start()
    daemons = _start_shards(tmp_path)
    try:
        router = ShardRouter({sid: LocalTransport(d)
                              for sid, d in daemons.items()})
        for lo in range(0, len(items), 16):
            s = single.ingest(items[lo:lo + 16])
            r = router.ingest(items[lo:lo + 16])
            assert s["ok"] and r["ok"]
            assert r["acked"] == s["acked"] == len(items[lo:lo + 16])
        single.quiesce()
        router.quiesce()
        qs = single.query(items)
        qr = router.query(items)
        assert bool(qs["known"].all()) and bool(qr["known"].all())
        assert _canon(qs["labels"]) == _canon(qr["labels"]), \
            "router min-merge partition diverged from the single daemon"
        # Both shards own part of the corpus (the parity is a fan-out
        # parity, not a one-shard degenerate case).
        owners = digest_range_ids(row_digests(items), N_SHARDS)
        assert len(np.unique(owners)) == N_SHARDS
        # Every ingested row is an index row; the STORE stays
        # content-addressed — planted exact dups appended no signatures.
        rows = sum(int(d._index.n_rows) for d in daemons.values())
        assert rows == int(single._index.n_rows) == len(items)
        store_rows = sum(int(d.store.n_rows) for d in daemons.values())
        assert store_rows == int(single.store.n_rows) == len(base)
    finally:
        single.stop(commit=False)
        _stop_shards(daemons)


def test_router_query_empty_batch(tmp_path):
    """A zero-row query batch min-merges to zero-row answers — no
    divide-by-shard, no index into an empty response."""
    daemons = _start_shards(tmp_path)
    try:
        router = ShardRouter({sid: LocalTransport(d)
                              for sid, d in daemons.items()})
        assert router.ingest(_unique_vectors(8, seed=61))["ok"]
        router.quiesce()
        q = router.query(np.empty((0, 16), np.uint32))
        assert q["labels"].shape == (0,)
        assert q["known"].shape == (0,)
        assert q["generation"] >= 1
    finally:
        _stop_shards(daemons)


def test_router_query_all_foreign_rows(tmp_path):
    """A FRESH router (empty global map — the failover shape: a
    replacement router restarting over live shards) still answers
    membership: every row known, every label the stable synthetic
    foreign id below -1, and the induced partition equals the original
    router's partition canonically."""
    base = _unique_vectors(20, seed=67)
    items = np.concatenate([base, base[[1, 4, 1]]])  # planted exact dups
    daemons = _start_shards(tmp_path)
    try:
        transports = {sid: LocalTransport(d)
                      for sid, d in daemons.items()}
        original = ShardRouter(transports)
        assert original.ingest(items)["ok"]
        original.quiesce()
        routed = original.query(items)
        fresh = ShardRouter(transports)  # no gmap: every label foreign
        q = fresh.query(items)
        assert bool(q["known"].all())
        assert bool((q["labels"] < -1).all()), \
            "foreign labels must be synthetic ids below -1"
        assert _canon(q["labels"]) == _canon(routed["labels"]), \
            "foreign min-merge partition diverged from the routed one"
    finally:
        _stop_shards(daemons)


def test_router_single_shard_topology_matches_unsharded_daemon(tmp_path):
    """N=1 is not a special case: a one-shard router is elementwise
    identical to talking to the daemon directly — same partition, same
    membership, same row accounting."""
    base = _unique_vectors(24, seed=71)
    items = np.concatenate([base, base[[2, 9]]])
    single = ServeDaemon(str(tmp_path / "single"), params=PARAMS).start()
    shard = ServeDaemon(str(tmp_path / "range_0000"), params=PARAMS,
                        state_commit_every=1).start()
    try:
        router = ShardRouter({0: LocalTransport(shard)})
        for lo in range(0, len(items), 10):
            s = single.ingest(items[lo:lo + 10])
            r = router.ingest(items[lo:lo + 10])
            assert s["ok"] and r["ok"] and r["acked"] == s["acked"]
        single.quiesce()
        router.quiesce()
        qs = single.query(items)
        qr = router.query(items)
        assert bool(qs["known"].all()) and bool(qr["known"].all())
        assert _canon(qr["labels"]) == _canon(qs["labels"])
        assert int(shard._index.n_rows) == int(single._index.n_rows)
        assert router.status()["shards"] == 1
    finally:
        single.stop(commit=False)
        shard.stop(commit=False)


def test_router_forward_drop_replays_ack_idempotently(tmp_path):
    """The lost-ack window: the shard committed and answered, the drop
    eats the answer before the router passes it up.  The retried SAME
    per-shard request id must be answered by the journal REPLAY — full
    ack, zero rows double-absorbed."""
    items = _unique_vectors(24, seed=9)
    daemons = _start_shards(tmp_path)
    try:
        router = ShardRouter({sid: LocalTransport(d)
                              for sid, d in daemons.items()})
        install_plan(FaultPlan([FaultRule(site="serve.router.forward",
                                          kind="connection_drop",
                                          times=1)]))
        try:
            r = router.ingest(items, request_id="drop-regress")
        finally:
            clear_plan()
        assert r["ok"] and r["acked"] == 24
        assert r.get("replayed"), "dropped ack was not replayed"
        rows = sum(int(d._index.n_rows) for d in daemons.values())
        assert rows == 24, f"double-absorb: {rows} rows from 24 uniques"
        q = router.query(items)
        assert bool(q["known"].all())
        st = router.status()
        assert st["router_replayed_acks"] >= 1
        assert st["router_rows"] == 24
    finally:
        _stop_shards(daemons)


def test_serve_client_over_router_server_carries_request_id(tmp_path):
    """The reconnect regression, end to end over TCP: a ServeClient
    ingest through a RouterServer with a drop injected at
    ``serve.router.forward`` still returns ONE full ack (the client's
    minted request id rides the retry; the shard replays).  The client
    code is byte-identical to the single-daemon topology."""
    items = _unique_vectors(18, seed=21)
    daemons = _start_shards(tmp_path)
    router = ShardRouter({sid: LocalTransport(d)
                          for sid, d in daemons.items()})
    server = RouterServer(router, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        with ServeClient(port=server.port) as c:
            assert c.ping()["ok"]
            install_plan(FaultPlan([FaultRule(site="serve.router.forward",
                                              kind="connection_drop",
                                              times=1)]))
            try:
                r = c.ingest(items, timeout_s=120)
            finally:
                clear_plan()
            assert r["ok"] and r["acked"] == 18
            rows = sum(int(d._index.n_rows) for d in daemons.values())
            assert rows == 18
            q = c.query(items, timeout_s=60)
            assert q["known"].all()
            st = c.status()
            assert st["topology"] == "sharded"
            assert st["shards"] == N_SHARDS
            assert c.quiesce(timeout_s=120)["ok"]
    finally:
        server.shutdown()
        server.server_close()
        _stop_shards(daemons)


# -- lease fencing ------------------------------------------------------------

def test_fenced_zombie_shard_writer_appends_zero_rows(tmp_path):
    """A superseded writer's next commit observes the advanced epoch
    and self-fences BEFORE the store append: zero rows written by the
    zombie, and the replacement (owning the new epoch) absorbs the same
    batch cleanly."""
    root = str(tmp_path)
    items = _unique_vectors(16, seed=33)
    guard = RangeLeaseGuard.claim(root, 0, owner=111)
    zombie = ServeDaemon(str(tmp_path / "range_0000"), params=PARAMS,
                         state_commit_every=1, lease_guard=guard).start()
    try:
        assert zombie.ingest(items[:8])["ok"]
        rows_before = int(zombie.store.n_rows)
        # Failover: the replacement claims the next epoch on range 0.
        replacement_guard = RangeLeaseGuard.claim(root, 0, owner=222)
        with pytest.raises(Exception):  # noqa: B017, PT011 — ticket wraps LeaseSupersededError
            zombie.ingest(items[8:], timeout=60)
        assert int(zombie.store.n_rows) == rows_before, \
            "fenced zombie writer appended rows"
        assert zombie._ingest_error is not None
    finally:
        zombie.stop(commit=False)
    replacement = ServeDaemon(str(tmp_path / "range_0000"), params=PARAMS,
                              state_commit_every=1,
                              lease_guard=replacement_guard).start()
    try:
        r = replacement.ingest(items[8:])
        assert r["ok"] and r["acked"] == 8
        assert bool(replacement.query(items)["known"].all())
    finally:
        replacement.stop(commit=False)


# -- read replicas ------------------------------------------------------------

def test_replica_staleness_bound_refresh_and_read_only(tmp_path):
    items = _unique_vectors(30, seed=41)
    src = str(tmp_path / "writer")
    dst = str(tmp_path / "replica")
    writer = ServeDaemon(src, params=PARAMS, state_commit_every=1).start()
    try:
        assert writer.ingest(items[:20])["ok"]
        writer.quiesce()
        stream_shards(src, dst)
        replica = ServeReplica(dst, params=PARAMS)
        assert replica_staleness(src, replica) == 0
        q = replica.query(items[:20])
        assert bool(q["known"].all())
        # Replica answers agree with the writer's partition.
        assert _canon(q["labels"]) == \
            _canon(writer.query(items[:20])["labels"])
        # Writer advances; the replica is STALE-BOUNDED, not wrong: old
        # rows still answer, new rows unknown until the next pull.
        assert writer.ingest(items[20:])["ok"]
        writer.quiesce()
        assert replica_staleness(src, replica) > 0
        lagged = replica.query(items)
        assert bool(lagged["known"][:20].all())
        assert not bool(lagged["known"][20:].any())
        stream_shards(src, dst)
        assert replica.refresh()
        assert replica_staleness(src, replica) == 0
        fresh = replica.query(items)
        assert bool(fresh["known"].all())
        # Write plane is fenced by construction.
        assert replica.read_only and replica.store.read_only
        with pytest.raises(RuntimeError, match="read replica"):
            replica.ingest(items[:1])
        with pytest.raises(RuntimeError):
            replica.quiesce()
        st = replica.status()
        assert st["read_only"] and st["generation_adopted"] >= 1
    finally:
        writer.stop(commit=False)


def test_replication_puller_converges(tmp_path):
    items = _unique_vectors(12, seed=55)
    src = str(tmp_path / "writer")
    dst = str(tmp_path / "replica")
    writer = ServeDaemon(src, params=PARAMS, state_commit_every=1).start()
    try:
        assert writer.ingest(items)["ok"]
        writer.quiesce()
        stream_shards(src, dst)
        replica = ServeReplica(dst, params=PARAMS)
        puller = ReplicationPuller(src, replica, interval_s=0.05)
        assert puller.pull_once() is False  # already fresh
        assert writer.ingest(_unique_vectors(6, seed=56))["ok"]
        writer.quiesce()
        assert puller.pull_once() is True
        assert replica_staleness(src, replica) == 0
        assert puller.pulls == 2
    finally:
        writer.stop(commit=False)


# -- the explorer over the new interleaving classes ---------------------------

def test_explore_router_and_replica_200_seeded_schedules():
    """The acceptance bar: >= 200 distinct seeded PCT schedules across
    the two NEW interleaving classes — router fan-out vs. concurrent
    shard writers (global label map, replay idempotence, zero
    double-absorb) and replica refresh vs. writer eviction/stream
    (committed-view adoption, generation monotonicity) — zero races."""
    stats_r = explore("router", n_seeded=105, exhaustive_bound=3)
    assert stats_r["trace_races_found"] == 0
    stats_p = explore("replica", n_seeded=105, exhaustive_bound=3)
    assert stats_p["trace_races_found"] == 0
    total = (stats_r["trace_schedules_explored"]
             + stats_p["trace_schedules_explored"])
    assert total >= 200
    assert (stats_r["trace_distinct_traces"]
            + stats_p["trace_distinct_traces"]) >= 8
