"""The resilience layer (tse1m_tpu/resilience/): retry engine, fault
plane, and the production seats threaded through them.

The contract under test (ISSUE acceptance): with a FaultPlan injecting
>= 3 transient failures at each I/O seat — HTTP fetch, DB execute,
checkpoint write — the *production* code paths (collect, ingest,
cluster_sessions_resumable) complete with output identical to a
fault-free run, with zero test-only branches in prod code.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pandas as pd
import pytest

from tse1m_tpu.config import Config
from tse1m_tpu.resilience import (FaultPlan, FaultRule, InjectedFault,
                                  RetryError, RetryPolicy, clear_plan,
                                  retry_call)
from tse1m_tpu.resilience.retry import RetryStats


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    clear_plan()
    yield
    clear_plan()


# -- retry engine -------------------------------------------------------------

class _Flaky:
    def __init__(self, fail_times, exc=None):
        self.fail_times = fail_times
        self.calls = 0
        self.exc = exc or OSError("transient")

    def __call__(self):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise self.exc
        return "ok"


def test_retry_succeeds_after_transients():
    fn = _Flaky(3)
    stats = RetryStats()
    got = retry_call(fn, policy=RetryPolicy(max_attempts=5, base_delay=0.01),
                     sleep=lambda s: None, stats=stats)
    assert got == "ok"
    assert fn.calls == 4
    assert stats.attempts == 4
    assert len(stats.sleeps) == 3


def test_retry_exhaustion_raises_retryerror_from_cause():
    fn = _Flaky(10)
    with pytest.raises(RetryError) as ei:
        retry_call(fn, policy=RetryPolicy(max_attempts=3, base_delay=0),
                   sleep=lambda s: None)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, OSError)
    assert fn.calls == 3


def test_retry_allowlist_propagates_other_exceptions_immediately():
    fn = _Flaky(1, exc=ValueError("not transient"))
    with pytest.raises(ValueError):
        retry_call(fn, policy=RetryPolicy(max_attempts=5, base_delay=0,
                                          retry_on=(OSError,)),
                   sleep=lambda s: None)
    assert fn.calls == 1


def test_retry_backoff_is_exponential_and_jitter_bounded():
    policy = RetryPolicy(max_attempts=6, base_delay=0.1, max_delay=0.5)
    # Deterministic steps without jitter:
    assert [policy.step(i) for i in range(4)] == [0.1, 0.2, 0.4, 0.5]
    stats = RetryStats()
    with pytest.raises(RetryError):
        retry_call(_Flaky(10), policy=policy, sleep=lambda s: None,
                   stats=stats)
    for i, slept in enumerate(stats.sleeps):
        assert 0 <= slept <= policy.step(i)


def test_retry_deadline_stops_before_budget_spent():
    clock = [0.0]

    def fake_sleep(s):
        clock[0] += s

    fn = _Flaky(50)
    with pytest.raises(RetryError) as ei:
        retry_call(fn, policy=RetryPolicy(max_attempts=50, base_delay=1.0,
                                          jitter=False, deadline=3.5),
                   sleep=fake_sleep, clock=lambda: clock[0])
    # backoff 1, 2 spends 3.0s; the next 4s step is cut to the remaining
    # 0.5s; then the deadline is exhausted.
    assert ei.value.attempts < 50
    assert clock[0] <= 3.5 + 1e-9


def test_retry_after_hint_raises_next_sleep():
    class Hinted(RuntimeError):
        retry_after = 7.5

    stats = RetryStats()
    with pytest.raises(RetryError):
        retry_call(_Flaky(5, exc=Hinted()),
                   policy=RetryPolicy(max_attempts=3, base_delay=0.01),
                   sleep=lambda s: None, stats=stats)
    assert all(s >= 7.5 for s in stats.sleeps)


def test_on_retry_recovery_hook_runs_between_attempts():
    seen = []
    fn = _Flaky(2)
    retry_call(fn, policy=RetryPolicy(max_attempts=4, base_delay=0),
               sleep=lambda s: None,
               on_retry=lambda exc, att: seen.append(att))
    assert seen == [0, 1]


# -- fault plane --------------------------------------------------------------

def test_fault_plan_counts_and_site_glob(tmp_path):
    plan = FaultPlan([FaultRule(site="db.*", times=2)])
    with plan.active():
        from tse1m_tpu.resilience import fault_point

        with pytest.raises(InjectedFault):
            fault_point("db.execute")
        with pytest.raises(InjectedFault):
            fault_point("db.connect")
        fault_point("db.execute")      # rule exhausted: pass through
        fault_point("http.fetch")      # never matched
    assert plan.fired == [("db.execute", "raise"), ("db.connect", "raise")]


def test_fault_plan_after_calls_skips_warmup():
    plan = FaultPlan([FaultRule(site="s", times=1, after_calls=2)])
    from tse1m_tpu.resilience import fault_point

    with plan.active():
        fault_point("s")
        fault_point("s")
        with pytest.raises(InjectedFault):
            fault_point("s")
        fault_point("s")


def test_fault_plan_json_roundtrip_and_env(tmp_path, monkeypatch):
    path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="db.execute", times=3)], seed=7).save(path)
    loaded = FaultPlan.from_json(path)
    assert loaded.seed == 7
    assert loaded.rules[0].site == "db.execute"
    assert loaded.rules[0].times == 3
    # env activation is what subprocess chaos tests rely on
    monkeypatch.setenv("TSE1M_FAULT_PLAN", path)
    import tse1m_tpu.resilience.faults as faults_mod

    monkeypatch.setattr(faults_mod, "_plan", None)
    monkeypatch.setattr(faults_mod, "_env_loaded", False)
    assert faults_mod.active_plan() is not None


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultRule(site="x", kind="explode")


def test_seeded_probability_is_deterministic():
    def run():
        plan = FaultPlan([FaultRule(site="s", times=-1, probability=0.5)],
                         seed=42)
        hits = []
        for _ in range(20):
            try:
                plan.fire("s")
                hits.append(0)
            except InjectedFault:
                hits.append(1)
        return hits

    a, b = run(), run()
    assert a == b
    assert 0 < sum(a) < 20


# -- HTTP seat ----------------------------------------------------------------

class _FakeResp:
    def __init__(self, status, content=b"", headers=None):
        self.status_code = status
        self.content = content
        self.headers = headers or {}

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"HTTP {self.status_code}")


class _UrlSession:
    """Serves scripted bytes by full URL; every request recorded."""

    def __init__(self, pages: dict):
        self.pages = pages
        self.requests = []

    def get(self, url, params=None, timeout=None):
        from tse1m_tpu.collect.transport import _with_params

        full = _with_params(url, params)
        self.requests.append(full)
        if full not in self.pages:
            return _FakeResp(404)
        return _FakeResp(200, self.pages[full])


def _fetcher(session, **kw):
    from tse1m_tpu.collect.transport import FetchPolicy, HttpFetcher

    kw.setdefault("backoff_factor", 0.0)
    return HttpFetcher(FetchPolicy(**kw), session=session)


def test_http_fetch_survives_injected_faults_with_identical_output():
    pages = {"https://x/a": b"payload"}
    clean = _fetcher(_UrlSession(pages), retries=0).get("https://x/a")
    plan = FaultPlan([FaultRule(site="http.fetch", times=3)])
    with plan.active():
        faulty = _fetcher(_UrlSession(pages), retries=3).get("https://x/a")
    assert len(plan.fired) == 3
    assert faulty.content == clean.content


def test_http_retry_after_header_is_honored_and_capped():
    from tse1m_tpu.collect.transport import FetchPolicy, HttpFetcher

    class _Scripted:
        def __init__(self, script):
            self.script = list(script)

        def get(self, url, params=None, timeout=None):
            return self.script.pop(0)

    session = _Scripted([
        _FakeResp(429, headers={"Retry-After": "3"}),
        _FakeResp(503, headers={"Retry-After": "9999"}),
        _FakeResp(200, b"done"),
    ])
    sleeps = []
    import tse1m_tpu.collect.transport as tmod

    f = HttpFetcher(FetchPolicy(retries=3, backoff_factor=0.0, deadline=30.0),
                    session=session)
    # Route the engine's sleep through a recorder (deadline still real).
    orig = tmod.retry_call

    def recording_retry(fn, **kw):
        kw["sleep"] = sleeps.append
        return orig(fn, **kw)

    tmod.retry_call = recording_retry
    try:
        resp = f.get("https://x/limited")
    finally:
        tmod.retry_call = orig
    assert resp.content == b"done"
    assert sleeps[0] >= 3.0          # server hint honored
    assert sleeps[1] <= 30.0         # capped at the policy deadline


def test_parse_retry_after_forms():
    from tse1m_tpu.collect.transport import parse_retry_after

    assert parse_retry_after("120") == 120.0
    assert parse_retry_after(" 0 ") == 0.0
    assert parse_retry_after("-5") == 0.0
    assert parse_retry_after(None) is None
    assert parse_retry_after("not a date or number") is None
    # HTTP-date in the past clamps to 0
    assert parse_retry_after("Wed, 21 Oct 2015 07:28:00 GMT") == 0.0


def test_http_hard_4xx_is_not_retried():
    class _Counting:
        def __init__(self):
            self.calls = 0

        def get(self, url, params=None, timeout=None):
            self.calls += 1
            return _FakeResp(403)

    session = _Counting()
    with pytest.raises(RuntimeError, match="HTTP 403"):
        _fetcher(session, retries=3).get("https://x/forbidden")
    assert session.calls == 1


# -- DB seat ------------------------------------------------------------------

def _db(tmp_path, name="r.sqlite", **cfg_kw):
    from tse1m_tpu.db.connection import DB

    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / name), **cfg_kw)
    return DB(config=cfg).connect()


def test_db_execute_survives_transient_faults(tmp_path):
    db = _db(tmp_path)
    db.execute("CREATE TABLE t (x INTEGER)")
    plan = FaultPlan([FaultRule(site="db.execute", times=3)])
    with plan.active():
        db.executeMany("INSERT INTO t VALUES (?)", [(i,) for i in range(5)])
        rows = db.query("SELECT COUNT(*) FROM t")
    assert rows == [(5,)]
    assert len(plan.fired) >= 3
    db.closeConnection()


def test_db_reconnects_on_dropped_connection(tmp_path):
    db = _db(tmp_path)
    db.execute("CREATE TABLE t (x INTEGER)")
    db.executeMany("INSERT INTO t VALUES (?)", [(1,), (2,)])
    before = db.connection
    plan = FaultPlan([FaultRule(site="db.execute", times=2,
                                kind="connection_drop")])
    with plan.active():
        rows = db.query("SELECT SUM(x) FROM t")
    assert rows == [(3,)]
    assert db.connection is not before  # a fresh connection was opened
    db.closeConnection()


def test_db_sql_errors_are_not_retried(tmp_path):
    db = _db(tmp_path)
    import sqlite3

    with pytest.raises(sqlite3.OperationalError):
        db.query("SELECT * FROM definitely_missing_table")
    db.closeConnection()


def test_db_statement_timeout_configured(tmp_path):
    db = _db(tmp_path, db_statement_timeout_ms=1234)
    (ms,) = db.connection.execute("PRAGMA busy_timeout").fetchone()
    assert ms == 1234
    db.closeConnection()


def test_db_open_caller_transaction_is_not_silently_retried(tmp_path):
    """A transient failure inside a caller-managed multi-statement
    transaction must surface: the retry engine's recovery rollback would
    silently drop the earlier uncommitted statements and a later
    ``commit()`` would persist a half-applied unit."""
    db = _db(tmp_path)
    db.execute("CREATE TABLE t (x INTEGER)")
    db.commit()
    db.execute("INSERT INTO t VALUES (1)")  # opens a caller transaction
    plan = FaultPlan([FaultRule(site="db.execute", times=1)])
    with plan.active():
        with pytest.raises(InjectedFault):
            db.execute("INSERT INTO t VALUES (2)")
    db.rollback()
    db.closeConnection()


def test_derive_projects_retries_whole_unit_without_duplicates(tmp_path):
    """REVIEW regression: a transient fault on derive_projects' INSERT
    must rerun the whole DELETE+INSERT unit — a per-statement retry rolls
    back the DELETE, replays only the INSERT, and commit then persists
    stale rows alongside new ones (duplicated projects)."""
    from tse1m_tpu.db.ingest import derive_projects
    from tse1m_tpu.db.schema import create_schema

    db = _db(tmp_path)
    create_schema(db)
    db.executeMany(
        "INSERT INTO buildlog_data (name, project, timecreated, build_type,"
        " result) VALUES (?, ?, ?, ?, ?)",
        [(f"n{i}", f"p{i}", "2024-01-01", "Fuzzing", "Finish")
         for i in range(3)])
    derive_projects(db)  # seed the stale rows a broken retry would keep
    # after_calls=1 lands the fault on the unit's second statement (the
    # INSERT), i.e. after the first attempt's DELETE already ran.
    plan = FaultPlan([FaultRule(site="db.execute", times=1, after_calls=1)])
    with plan.active():
        derive_projects(db)
    assert plan.fired == [("db.execute", "raise")]
    rows = db.query("SELECT project_name FROM projects ORDER BY project_name")
    assert rows == [("p0",), ("p1",), ("p2",)]
    db.closeConnection()


def test_restore_insert_dump_survives_db_faults(tmp_path):
    """REVIEW regression: each dump INSERT commits as its own unit, so a
    mid-stream transient failure (or dropped connection) cannot silently
    discard previously-streamed uncommitted rows."""
    from tse1m_tpu.db.restore import restore_sql_dump

    dump = tmp_path / "dump.sql"
    dump.write_text("\n".join(
        "INSERT INTO buildlog_data (name, project, timecreated, build_type,"
        f" result) VALUES ('n{i}', 'p', '2024-01-01', 'Fuzzing', 'Finish');"
        for i in range(6)) + "\n")

    clean_db = _db(tmp_path, name="clean.sqlite")
    clean = restore_sql_dump(clean_db, str(dump))
    clean_rows = clean_db.query(
        "SELECT name, result FROM buildlog_data ORDER BY name")
    clean_db.closeConnection()

    faulty_db = _db(tmp_path, name="faulty.sqlite")
    plan = FaultPlan([
        FaultRule(site="db.execute", times=2, after_calls=9),
        FaultRule(site="db.execute", times=1, kind="connection_drop",
                  after_calls=14),
    ])
    with plan.active():
        faulty = restore_sql_dump(faulty_db, str(dump))
    faulty_rows = faulty_db.query(
        "SELECT name, result FROM buildlog_data ORDER BY name")
    faulty_db.closeConnection()

    assert len(plan.fired) >= 3
    assert faulty == clean
    assert faulty_rows == clean_rows


def test_config_fault_plan_is_installed_at_cli_startup(tmp_path, monkeypatch):
    """REVIEW regression: an INI-configured `fault_plan` must actually
    activate (previously only TSE1M_FAULT_PLAN was consumed)."""
    from tse1m_tpu.cli import _activate_config_fault_plan
    from tse1m_tpu.resilience import active_plan

    path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="nowhere.*", times=1)], seed=3).save(path)
    ini = tmp_path / "env.ini"
    ini.write_text(f"[FRAMEWORK]\nfault_plan = {path}\n")
    monkeypatch.setenv("TSE1M_ENVFILE", str(ini))
    monkeypatch.delenv("TSE1M_FAULT_PLAN", raising=False)
    try:
        _activate_config_fault_plan()
        plan = active_plan()
        assert plan is not None and plan.seed == 3
        # exported so chaos-test subprocesses inherit the same plan
        assert os.environ.get("TSE1M_FAULT_PLAN") == path
    finally:
        os.environ.pop("TSE1M_FAULT_PLAN", None)


def test_kill_rule_never_falls_through_to_catchable_fault(monkeypatch):
    """REVIEW regression: if SIGKILL delivery is not immediate, the kill
    branch must not fall through and raise InjectedFault instead."""
    import signal

    delivered = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: delivered.append(sig))
    plan = FaultPlan([FaultRule(site="s", kind="kill")])
    with pytest.raises(SystemExit):
        plan.fire("s")
    assert delivered == [signal.SIGKILL]


def test_ingest_under_db_faults_matches_fault_free(tmp_path):
    from tse1m_tpu.data.synth import SynthSpec, generate_study
    from tse1m_tpu.db.ingest import ingest_csv_dir

    study = generate_study(SynthSpec(n_projects=3, days=40, seed=5))
    csv_dir = str(tmp_path / "csv")
    study.to_csv_dir(csv_dir)

    clean_db = _db(tmp_path, name="clean.sqlite")
    clean_counts = ingest_csv_dir(clean_db, csv_dir)
    clean_rows = clean_db.query(
        "SELECT * FROM buildlog_data ORDER BY rowid")
    clean_db.closeConnection()

    faulty_db = _db(tmp_path, name="faulty.sqlite")
    plan = FaultPlan([
        FaultRule(site="db.execute", times=2),
        FaultRule(site="db.execute", times=1, kind="connection_drop",
                  after_calls=4),
    ])
    with plan.active():
        faulty_counts = ingest_csv_dir(faulty_db, csv_dir)
    faulty_rows = faulty_db.query(
        "SELECT * FROM buildlog_data ORDER BY rowid")
    faulty_db.closeConnection()

    assert len(plan.fired) >= 3
    assert faulty_counts == clean_counts
    assert faulty_rows == clean_rows


# -- checkpoint seats ---------------------------------------------------------

def test_csv_checkpointer_survives_injected_torn_writes(tmp_path):
    from tse1m_tpu.collect.checkpoint import CsvBatchCheckpointer

    def run(directory, plan=None):
        ctx = plan.active() if plan else None
        if ctx:
            ctx.__enter__()
        try:
            ck = CsvBatchCheckpointer(str(directory), "b", batch_size=3,
                                      fieldnames=["id", "v"])
            for i in range(10):
                ck.add({"id": i, "v": f"row{i}"})
            final = str(directory / "final.csv")
            ck.merge(final)
        finally:
            if ctx:
                ctx.__exit__(None, None, None)
        return pd.read_csv(final)

    clean = run(tmp_path / "clean")
    plan = FaultPlan([
        FaultRule(site="checkpoint.csv.flush", times=2, kind="torn_write"),
        FaultRule(site="checkpoint.csv.flush", times=1, after_calls=3),
    ])
    faulty = run(tmp_path / "faulty", plan)
    assert len(plan.fired) >= 3
    pd.testing.assert_frame_equal(faulty, clean)


def test_cluster_resumable_survives_injected_faults(tmp_path):
    from tse1m_tpu.cluster import (ClusterParams, cluster_sessions,
                                   cluster_sessions_resumable)
    from tse1m_tpu.data.synth import synth_session_sets

    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                           h2d_chunks=4)
    items = synth_session_sets(2048, set_size=16, seed=3)[0]
    want = cluster_sessions(items, params)
    plan = FaultPlan([
        FaultRule(site="checkpoint.cluster.save", times=2,
                  kind="torn_write"),
        FaultRule(site="checkpoint.cluster.save", times=1, after_calls=2),
    ])
    with plan.active():
        got = cluster_sessions_resumable(
            items, params, checkpoint_dir=str(tmp_path / "ck"))
    assert len(plan.fired) >= 3
    np.testing.assert_array_equal(got, want)


def test_collect_under_http_faults_matches_fault_free(tmp_path):
    """The acceptance seat for `collect`: the GCS metadata pager walks
    pages through HttpFetcher while the plan injects >= 3 transient
    failures; the merged CSV must equal the fault-free run's."""
    from tse1m_tpu.collect.gcs_metadata import (API_URL_TEMPLATE,
                                                GcsMetadataCollector)

    url = API_URL_TEMPLATE.format(bucket="oss-fuzz-gcb-logs")
    uuid = "0f8b9a2c-1111-2222-3333-44445555666"
    page = lambda items, token: json.dumps(  # noqa: E731
        {"items": items, **({"nextPageToken": token} if token else {})}
    ).encode()
    items1 = [{"name": f"log-{uuid}{d}.txt", "selfLink": "s", "mediaLink":
               "m", "size": "1", "timeCreated": "t"} for d in "012"]
    items2 = [{"name": f"log-{uuid}{d}.txt", "selfLink": "s", "mediaLink":
               "m", "size": "2", "timeCreated": "t"} for d in "345"]
    pages = {url: page(items1, "tok2"),
             url + "?pageToken=tok2": page(items2, None)}

    def run(sub, plan=None):
        fetcher = _fetcher(_UrlSession(pages), retries=4)
        coll = GcsMetadataCollector(fetcher, str(tmp_path / sub / "batches"))
        final = str(tmp_path / sub / "meta.csv")
        if plan:
            with plan.active():
                n = coll.collect(final)
        else:
            n = coll.collect(final)
        return n, pd.read_csv(final)

    n_clean, clean = run("clean")
    plan = FaultPlan([FaultRule(site="http.fetch", times=3)])
    n_faulty, faulty = run("faulty", plan)
    assert len(plan.fired) == 3
    assert n_faulty == n_clean == 6
    pd.testing.assert_frame_equal(faulty, clean)
