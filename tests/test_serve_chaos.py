"""Serving-plane chaos: SIGKILL mid-ingest durability and bounded
memory under sustained ingest with LRU store eviction active.

The SIGKILL game-day runs the REAL daemon subprocess (chaos_drivers
``serve``) with the fault plane's ``kill`` rule at the
``serve.ingest.commit`` production seat — the deterministic point
BEFORE a batch's store append commits — and asserts the durability
contract end to end: every ACKNOWLEDGED batch survives, the killed
(unacknowledged) batch recomputes on re-ingest, and post-quiesce
membership answers equal a cold batch run elementwise
(tests/serve_harness.py; the CI fault-matrix ``serve-kill`` seat runs
the same round).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from serve_harness import REPO, serve_kill_round, sharded_kill_round


def test_sigkill_mid_ingest_zero_lost_acked_rows(tmp_path):
    r = serve_kill_round(str(tmp_path))
    assert r["lost_acked"] == 0
    assert r["acked_before_kill"] == 300
    assert r["rows"] == 900


def test_sharded_sigkill_midround_zero_lost_acks(tmp_path):
    """The sharded failover game-day: SIGKILL shard 0 mid-ingest at its
    ``serve.ingest.commit`` seat while the parent routes through a
    ShardRouter over TCP; a watcher respawns the replacement writer
    (next lease epoch) and the router's retried in-flight slice — same
    request id — lands on it.  Zero lost acked rows, zero
    double-absorbs, labels elementwise-equal to an uninterrupted
    sharded run (serve_harness.sharded_kill_round; the CI fault-matrix
    ``router-shard-kill`` seat runs the same round)."""
    r = sharded_kill_round(str(tmp_path))
    assert r["lost_acked"] == 0
    assert r["rows"] == r["oracle_rows"]
    assert r["acked_batches"] == 6


def test_rss_bounded_under_sustained_ingest_with_lru(tmp_path):
    """Sustained ingest must not accrete signature bytes as anonymous
    heap: signatures live in the store (file-backed, LRU-evicted under
    TSE1M_SIG_STORE_MAX_MB); the process owns only the live index
    (labels/locator/digest map/band tables — O(rows), small).  The pin:
    late-phase RssAnon growth per batch stays in the index's ~100 B/row
    envelope, nowhere near the ~512 B/row of signatures, while LRU
    eviction demonstrably fired and known-row queries keep answering."""
    child = r"""
import json, os, sys
import numpy as np
from tse1m_tpu.cluster import ClusterParams
from tse1m_tpu.data.synth import synth_session_sets
from tse1m_tpu.serve import ServeDaemon

def anon_kb():
    with open('/proc/self/status') as f:
        for line in f:
            if line.startswith('RssAnon:'):
                return int(line.split()[1])
    raise RuntimeError('no RssAnon')

params = ClusterParams(n_hashes=128, n_bands=16, use_pallas="never")
dm = ServeDaemon(sys.argv[1], params=params, state_commit_every=10**6)
dm.start()
batch, warm_batches, total_batches = 1024, 8, 48
probe = None
marks = {}
for i in range(total_batches):
    rows = synth_session_sets(batch, set_size=32, seed=100 + i,
                              dup_fraction=0.0)[0]
    if probe is None:
        probe = rows[:64].copy()
    r = dm.ingest(rows, timeout=600)
    assert r["ok"], r
    if i + 1 == warm_batches:
        marks["warm_kb"] = anon_kb()
res = dm.query(probe)
assert bool(res["known"].all()), "known rows lost under eviction"
marks["end_kb"] = anon_kb()
marks["evicted"] = dm.store.n_rows < (total_batches * batch)
marks["store_rows"] = int(dm.store.n_rows)
marks["index_rows"] = int(dm._index.n_rows)
dm.stop(commit=False)
print(json.dumps(marks))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TSE1M_SIG_STORE_MAX_MB="4")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", child, str(tmp_path / "store")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-3000:]
    marks = json.loads(proc.stdout.strip().splitlines()[-1])
    assert marks["evicted"], marks  # LRU actually fired
    assert marks["store_rows"] * 128 * 4 <= 4 * 2**20, marks  # bounded
    assert marks["index_rows"] == 48 * 1024
    grown_rows = (48 - 8) * 1024
    delta_kb = marks["end_kb"] - marks["warm_kb"]
    # Anonymous growth per ingested row must stay inside the LIVE-INDEX
    # envelope (~160 B/row of labels/locator/digest-map/band-tables plus
    # allocator churn; ~450-580 B/row measured under the suite's
    # 8-virtual-device XLA_FLAGS, incl. the graftrace seat constants) —
    # NOT the ~512 B/row of signature bytes, which live in the
    # LRU-bounded file-backed store.  If signatures (or unbounded probe
    # indexes) accreted on the heap, per-row growth would land at
    # >= ~0.95 KB/row — well past this bound, so the pin keeps its
    # detection power with headroom for allocator variance.
    assert delta_kb < grown_rows * 0.7, (delta_kb, grown_rows, marks)
