"""RQ4b: backend parity, DB-replay oracles, artifacts (both backends)."""

import os

import numpy as np
import pandas as pd
import pytest

from tse1m_tpu.analysis.corpus import load_corpus_groups
from tse1m_tpu.analysis.rq4b import (PERCENTILES, coverage_deltas,
                                     initial_coverage_stats, run_rq4b,
                                     session_bm_pvalues, summarize_trends)
from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend, floor_day_ns
from tse1m_tpu.config import Config
from tse1m_tpu.data.columnar import StudyArrays

LIMIT = "2026-01-01"
DAY_NS = 86_400_000_000_000


@pytest.fixture(scope="module")
def arrays(study_db):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT)
    return StudyArrays.from_db(study_db, cfg)


@pytest.fixture(scope="module")
def limit_ns():
    return int(np.datetime64(LIMIT, "ns").astype(np.int64))


@pytest.fixture(scope="module")
def corpus_csv(synth_study, tmp_path_factory):
    path = tmp_path_factory.mktemp("corpus") / "project_corpus_analysis.csv"
    synth_study.corpus_analysis.to_csv(path, index=False)
    return str(path)


@pytest.fixture(scope="module")
def groups(corpus_csv, arrays):
    return load_corpus_groups(corpus_csv, set(arrays.projects))


@pytest.fixture(scope="module")
def group_indices(groups, arrays):
    pidx = arrays.project_index()
    return groups.indices("group1", pidx), groups.indices("group2", pidx)


@pytest.mark.parametrize("mesh", [None, "auto"],
                         ids=["single-device", "mesh"])
def test_trends_backend_parity(arrays, limit_ns, group_indices, mesh):
    """Bit-exact parity — the percentile values feed summarize_trends' G2>G1
    win counts, which flip on any rounding divergence (ADVICE r1)."""
    g1, g2 = group_indices
    res_pd = PandasBackend().rq4b_group_trends(arrays, limit_ns, g1, g2,
                                               PERCENTILES)
    res_jx = JaxBackend(mesh=mesh).rq4b_group_trends(arrays, limit_ns, g1, g2,
                                                     PERCENTILES)
    assert res_pd.matrix.shape == res_jx.matrix.shape
    assert res_pd.matrix.shape[1] > 0
    for f in ("matrix", "mask", "g1_percentiles", "g1_counts",
              "g2_percentiles", "g2_counts"):
        np.testing.assert_array_equal(getattr(res_pd, f), getattr(res_jx, f),
                                      err_msg=f)
    # ... and therefore identical downstream win counts / Spearman summary.
    p_pd = session_bm_pvalues(res_pd, g1, g2)
    p_jx = session_bm_pvalues(res_jx, g1, g2)
    s_pd = summarize_trends(res_pd, p_pd, min_projects=2)
    s_jx = summarize_trends(res_jx, p_jx, min_projects=2)
    assert s_pd["wins"] == s_jx["wins"]
    assert s_pd["bm_significant"] == s_jx["bm_significant"]


def test_trend_matrix_oracle(arrays, limit_ns, study_db):
    """Replay the reference's per-project trend extraction
    (rq4b_coverage.py:914-936) from raw DB rows: non-null > 0 coverage rows
    before the cutoff, densely session-indexed per project."""
    res = PandasBackend().rq4b_group_trends(
        arrays, limit_ns, np.arange(arrays.n_projects), np.array([], np.int64),
        PERCENTILES)
    for p, name in enumerate(arrays.projects):
        rows = study_db.query(
            "SELECT coverage FROM total_coverage WHERE project=? AND date<? "
            "AND coverage IS NOT NULL AND coverage > 0 ORDER BY date",
            (name, LIMIT))
        trend = np.array([r[0] for r in rows], dtype=np.float64)
        got = res.matrix[p][res.mask[p]]
        np.testing.assert_array_equal(got, trend, err_msg=name)
    # g1 == all projects here: percentiles must match np.percentile per
    # session over the raw columns.
    S = res.matrix.shape[1]
    for s in range(0, S, max(1, S // 7)):
        col = res.matrix[:, s][res.mask[:, s]]
        np.testing.assert_array_equal(res.g1_percentiles[:, s],
                                      np.percentile(col, PERCENTILES))
        assert res.g1_counts[s] == col.size


def test_coverage_deltas_oracle(arrays, limit_ns, groups, study_db):
    """Replay the reference's pre/post delta semantics (rq4b:744-794): last /
    first N positive coverage rows strictly before / from the corpus *day*,
    deltas relative to Pre-1."""
    N = 7
    deltas = coverage_deltas(arrays, groups, N)
    target = groups.groups["group3"] | groups.groups["group4"]
    pidx = arrays.project_index()
    expected_kept = []
    for name in sorted(target):
        t_corpus = groups.corpus_time_ns.get(name)
        if t_corpus is None or name not in pidx:
            continue
        rows = study_db.query(
            "SELECT date, coverage FROM total_coverage WHERE project=? "
            "AND coverage IS NOT NULL AND coverage > 0 ORDER BY date", (name,))
        # extraction window mirrors StudyArrays: date < limit + 1 day
        limit_plus = pd.Timestamp(limit_ns + DAY_NS)
        rows = [(pd.Timestamp(d), c) for d, c in rows
                if pd.Timestamp(d) < limit_plus]
        corpus_day = pd.Timestamp(floor_day_ns(np.int64(t_corpus)))
        pre = [c for d, c in rows if d < corpus_day][-N:][::-1]
        post = [c for d, c in rows if d >= corpus_day][:N]
        if len(pre) < N or len(post) < N:
            assert name not in deltas["projects"]
            if len(pre) == 0:
                assert name in deltas["missing_pre"]
            continue
        expected_kept.append(name)
        i = deltas["projects"].index(name)
        np.testing.assert_allclose(deltas["pre_coverages"][i], pre)
        np.testing.assert_allclose(deltas["post_coverages"][i], post)
        base = pre[0]
        np.testing.assert_allclose(deltas["pre_deltas"][i],
                                   [base - v for v in pre])
        np.testing.assert_allclose(deltas["post_deltas"][i],
                                   [v - base for v in post])
        expect_g = 4 if name in groups.groups["group4"] else 3
        assert deltas["group_num"][i] == expect_g
    assert deltas["projects"] == expected_kept
    assert len(expected_kept) > 0, "fixture produced no pre/post cohort"


def test_initial_coverage_stats_empty():
    out = initial_coverage_stats(np.array([]), np.array([1.0, 2.0]))
    assert out == {"n_g2": 0, "n_g1": 2}


@pytest.mark.parametrize("backend", ["pandas", "jax_tpu", "auto"])
def test_run_rq4b_end_to_end(study_db, tmp_path, corpus_csv, backend):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 backend=backend, result_dir=str(tmp_path / backend),
                 limit_date=LIMIT, corpus_csv=corpus_csv,
                 min_projects_per_iteration=2)
    out = run_rq4b(cfg, db=study_db)
    df = pd.read_csv(out["trend_csv"])
    assert df.columns[0] == "Session"
    assert {"G2_25", "G2_50", "G2_75", "G2_Count", "G1_25", "G1_50", "G1_75",
            "G1_Count", "BM_p_value"} <= set(df.columns)
    assert len(df) == out["result"].matrix.shape[1]
    assert out["summary"]["valid_sessions"] > 0
    assert {"n_g2", "n_g1"} <= set(out["initial_stats"])
    base = tmp_path / backend / "rq4" / "coverage"
    for pdf in ("coverage_delta_timeseries_linear.pdf",
                "g2_g1_boxplot_comparison.pdf"):
        assert os.path.exists(base / pdf)


def test_run_rq4b_empty_study(tmp_path, corpus_csv):
    """An empty trend matrix must degrade to n_g2 = n_g1 = 0, not IndexError
    (ADVICE r1)."""
    from tse1m_tpu.data.synth import SynthSpec, generate_study
    from tse1m_tpu.db.connection import DB

    path = str(tmp_path / "empty.sqlite")
    cfg = Config(engine="sqlite", sqlite_path=path, backend="pandas",
                 result_dir=str(tmp_path / "out"), corpus_csv=corpus_csv,
                 limit_date="2000-01-02")
    db = DB(config=cfg).connect()
    generate_study(SynthSpec(n_projects=3, days=30, seed=1)).to_db(db)
    try:
        out = run_rq4b(cfg, db=db)
    finally:
        db.closeConnection()
    assert out["initial_stats"] == {"n_g2": 0, "n_g1": 0}
    assert out["summary"] == {"valid_sessions": 0}
