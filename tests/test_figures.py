"""Exercise the data-gated figure writers.

Three reference artifacts are produced only when per-session project
counts clear the study's >=100 filter (rq2_coverage_count.py:386-435,
rq2:123-242, rq4b_coverage.py:491-723):

- rq2/session_coverage_boxplot.pdf
- rq2/session_coverage_distribution_trend.pdf
- rq4/coverage/g2_g1_boxplot_comparison.pdf

On the small synth studies every other test uses, those filters gate the
writers off — so without this file no CI run ever executes them.  Here the
drivers run in test_mode (min_projects -> 1, mirroring the reference's
TEST_MODE switch rq1_detection_rate.py:20,233) and the full artifact set is
asserted present and non-trivial.
"""

from __future__ import annotations

import os

import pytest

from tse1m_tpu.analysis.rq2_trends import run_rq2_trends
from tse1m_tpu.analysis.rq4b import run_rq4b
from tse1m_tpu.config import Config


@pytest.fixture(scope="module")
def figure_run(study_db, synth_study, tmp_path_factory):
    out = tmp_path_factory.mktemp("figures")
    corpus = out / "project_corpus_analysis.csv"
    synth_study.corpus_analysis.to_csv(corpus, index=False)
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date="2026-01-01", backend="jax_tpu",
                 result_dir=str(out), corpus_csv=str(corpus))
    cfg.test_mode = True  # min_projects -> 1 (reference TEST_MODE semantics)
    run_rq2_trends(cfg, db=study_db)
    run_rq4b(cfg, db=study_db)
    return str(out)


def _assert_pdf(path):
    assert os.path.exists(path), f"missing figure: {path}"
    assert os.path.getsize(path) > 1024, f"implausibly small PDF: {path}"


def test_rq2_gated_figures_written(figure_run):
    _assert_pdf(os.path.join(figure_run, "rq2",
                             "session_coverage_boxplot.pdf"))
    _assert_pdf(os.path.join(figure_run, "rq2",
                             "session_coverage_distribution_trend.pdf"))
    # The always-on rq2 figures come out of the same run.
    _assert_pdf(os.path.join(figure_run, "rq2", "all_project_corr_hist.pdf"))
    _assert_pdf(os.path.join(figure_run, "rq2",
                             "average_median_lineplot.pdf"))


def test_rq4b_gated_boxplot_written(figure_run):
    _assert_pdf(os.path.join(figure_run, "rq4", "coverage",
                             "g2_g1_boxplot_comparison.pdf"))


class _FakePrePost:
    """Just enough surface for plot_transition_venn."""

    kept_projects = ["a", "b", "c", "d", "e"]

    @staticmethod
    def transition_counts():
        return {"pre_only": 2, "post_only": 1, "pre_and_post": 1,
                "no_detection": 1}


@pytest.mark.parametrize("with_venn", [True, False])
def test_rq4a_venn_writer_both_paths(tmp_path, monkeypatch, with_venn):
    """plot_transition_venn must emit a PDF whether matplotlib-venn is
    installed or not (the reference hard-requires it, requirements.txt;
    our writer falls back to raw matplotlib circles)."""
    from tse1m_tpu.analysis import rq4a

    if not with_venn:
        # A None entry makes `from matplotlib_venn import venn2` raise
        # ImportError even when the real package is installed.
        monkeypatch.setitem(__import__("sys").modules, "matplotlib_venn",
                            None)
    else:
        pytest.importorskip("matplotlib_venn")
    path = tmp_path / f"venn_{with_venn}.pdf"
    rq4a.plot_transition_venn(_FakePrePost(), str(path))
    _assert_pdf(str(path))
