"""Native Postgres COPY-binary decoder (native/pg_decode.cc) — the
server-independent half: the stream parser against crafted frames per the
documented format, and the COPY wrapper SQL builder.  The transport +
end-to-end parity run under test_postgres_live.py where a server exists."""

from __future__ import annotations

import struct

import numpy as np
import pytest

from tse1m_tpu.data.columnar import _inline_params, _pg_copy_sql
from tse1m_tpu.native import parse_copy_binary

PG_EPOCH_NS = 946684800 * 10**9


def _stream(rows, ncol):
    out = b"PGCOPY\n\xff\r\n\x00" + struct.pack(">ii", 0, 0)
    for row in rows:
        out += struct.pack(">h", ncol)
        for cell in row:
            if cell is None:
                out += struct.pack(">i", -1)
            else:
                out += struct.pack(">i", len(cell)) + cell
    return out + struct.pack(">h", -1)


def _ts(us):
    return struct.pack(">q", us)


def _f8(v):
    return struct.pack(">d", v)


def _d4(days):
    return struct.pack(">i", days)


@pytest.fixture(autouse=True)
def _need_native():
    try:
        out = parse_copy_binary(b"", "p", [])
    except RuntimeError:
        return  # module built — the empty stream is rejected, as expected
    if out is None:  # module didn't build (no g++ etc.)
        pytest.skip("native pg decoder unavailable")


def test_parse_all_spec_chars():
    rows = [
        [b"alpha", _ts(1_000_000), _f8(42.5), b"Finish", b"{a,b}",
         b"log-1.txt", b"123"],
        [b"beta", _ts(0), None, b"Finish", None, b"log-2.txt", None],
        [b"alpha", _d4(3), _f8(-1.0), None, b"{c}", None, b"9"],
    ]
    proj, t, f, s, c, b, o = parse_copy_binary(
        _stream(rows, 7), "ptfscbo", ["alpha", "beta"])
    np.testing.assert_array_equal(proj, [0, 1, 0])
    assert t[0] == PG_EPOCH_NS + 1_000_000_000
    assert t[1] == PG_EPOCH_NS
    assert t[2] == PG_EPOCH_NS + 3 * 86400 * 10**9  # DATE width
    assert f[0] == 42.5 and np.isnan(f[1]) and f[2] == -1.0
    assert list(s) == ["Finish", "Finish", None]
    codes, vocab = c
    np.testing.assert_array_equal(codes, [0, -1, 1])
    assert vocab == ["{a,b}", "{c}"]
    arena, starts, lens = b
    assert bytes(arena[starts[0]:starts[0] + lens[0]]) == b"log-1.txt"
    assert lens[2] == -1
    assert list(o) == ["123", None, "9"]


def test_parse_rejects_malformed():
    good = _stream([[b"alpha"]], 1)
    cases = [
        (b"NOTPGCOPY" + good[9:], "signature"),
        (good[:-2], "trailer"),
        (_stream([[b"zulu"]], 1), "key value"),
        (_stream([[b"alpha", b"x"]], 2), "field count"),
        (_stream([[struct.pack(">h", 1)]], 1), "timestamp width"),
    ]
    specs = ["p", "p", "p", "p", "t"]
    for (data, msg), spec in zip(cases, specs):
        with pytest.raises(RuntimeError, match=msg):
            parse_copy_binary(data, spec, ["alpha"])


def test_parse_rejects_infinity_timestamp():
    inf = struct.pack(">q", 2**63 - 1)
    with pytest.raises(RuntimeError, match="infinity"):
        parse_copy_binary(_stream([[inf]], 1), "t", [])


def test_inline_params():
    sql = "SELECT * FROM t WHERE a IN (?, ?) AND b < ? AND c = ?"
    out = _inline_params(sql, ("x", "o'brien", 5, None))
    assert out == ("SELECT * FROM t WHERE a IN ('x', 'o''brien') "
                   "AND b < 5 AND c = NULL")
    with pytest.raises(ValueError):
        _inline_params("SELECT ?", ("a", "b"))


def test_pg_copy_sql_casts_and_aliases():
    sql = _pg_copy_sql("SELECT project, covered_line FROM t WHERE p = ?",
                       ("x",), "pf")
    # positional aliases decouple the wrapper from inner column names;
    # text-spec'd columns cast ::text, numeric ones stay binary
    assert 'AS q("c0", "c1")' in sql
    assert 'q."c0"::text' in sql and 'q."c1"::text' not in sql
    assert sql.startswith("COPY (SELECT")
    assert sql.endswith("TO STDOUT (FORMAT binary)")
    assert "'x'" in sql
