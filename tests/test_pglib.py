"""Offline units of the ctypes libpq driver (db/pglib.py): placeholder
rewrite, parameter adaption, OID conversion, array literal round-trip.
The transport itself needs a live server (test_postgres_live.py)."""

from __future__ import annotations

import datetime as dt

from tse1m_tpu.db import pglib


def test_format_to_dollar_basic():
    assert pglib.format_to_dollar(
        "SELECT * FROM t WHERE a = %s AND b = %s") == \
        "SELECT * FROM t WHERE a = $1 AND b = $2"


def test_format_to_dollar_skips_literals_and_comments():
    sql = ("SELECT '%s literal', 'it''s %s' -- trailing %s comment\n"
           "FROM t WHERE x = %s AND y = '100%%' AND z = %s")
    out = pglib.format_to_dollar(sql)
    assert "$1" in out and "$2" in out and "$3" not in out
    assert "'%s literal'" in out and "'it''s %s'" in out
    assert "-- trailing %s comment" in out


def test_format_to_dollar_percent_escape():
    # %% outside literals unescapes; inside a literal it stays verbatim
    assert pglib.format_to_dollar("SELECT %s, 100%%") == "SELECT $1, 100%"
    assert pglib.format_to_dollar("LIKE 'x' || %s || '%%'") \
        == "LIKE 'x' || $1 || '%%'"


def test_adapt_param():
    assert pglib.adapt_param(None) is None
    assert pglib.adapt_param(True) == b"t"
    assert pglib.adapt_param(False) == b"f"
    assert pglib.adapt_param(42) == b"42"
    assert pglib.adapt_param(1.5) == b"1.5"
    assert pglib.adapt_param("x'y") == b"x'y"
    assert pglib.adapt_param(dt.datetime(2023, 6, 1, 12, 30)) \
        == b"2023-06-01T12:30:00"
    assert pglib.adapt_param(["a", 'b"c', None]) == b'{"a","b\\"c",NULL}'


def test_array_literal_roundtrip():
    items = ["plain", "with,comma", 'with"quote', "with\\back", ""]
    lit = pglib.compose_array(items)
    assert pglib.parse_text_array(lit) == items
    assert pglib.parse_text_array("{}") == []
    assert pglib.parse_text_array("{a,NULL,c}") == ["a", None, "c"]


def test_convert_cell_by_oid():
    c = pglib.convert_cell
    assert c(23, "7") == 7 and isinstance(c(20, "9"), int)
    assert c(701, "1.25") == 1.25
    assert c(1700, "10.5") == 10.5
    assert c(16, "t") is True and c(16, "f") is False
    assert c(25, "text stays") == "text stays"
    assert c(1082, "2023-06-01") == dt.date(2023, 6, 1)
    ts = c(1114, "2023-06-01 12:30:45.5")
    assert ts == dt.datetime(2023, 6, 1, 12, 30, 45, 500000)
    tstz = c(1184, "2023-06-01 12:30:45+02")
    assert tstz.utcoffset() == dt.timedelta(hours=2)
    tstz2 = c(1184, "2023-06-01 12:30:45-05:30")
    assert tstz2.utcoffset() == -dt.timedelta(hours=5, minutes=30)
    assert c(1009, '{a,"b,c"}') == ["a", "b,c"]


def test_libpq_loads_on_this_image():
    """The image ships libpq.so.5; the binding must come up (this is what
    unlocks engine=postgres without psycopg2)."""
    assert pglib.available()


def test_connect_refused_raises_cleanly():
    """No server on this box: connect must raise pglib.Error promptly (the
    live-test gate depends on this failing fast, not hanging)."""
    import pytest

    if not pglib.available():
        pytest.skip("libpq not present")
    with pytest.raises(pglib.Error):
        pglib.connect(database="nope", user="nope", password="nope",
                      host="127.0.0.1", port=59999)