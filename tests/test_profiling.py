"""graftprof (observability/profiling.py): sampler lifecycle + kill
switch, span/plane attribution, lock-wait histograms per site, the
atomic numbered profile artifact, slow-request capture with the
absorbing site named, and the serve-plane slowlog/profile verbs.

The load-bearing claims:

- ``TSE1M_PROFILING=0`` (or ``set_profiling(False)``) means NO sampling
  threads exist — start refuses, a live sampler loop exits, and the
  lock-wait recorder detaches;
- every contended traced-lock site shows up in ``lock_wait_seconds``
  under its own name (the recorder buffers and never deadlocks on the
  registry's own lock — the regression test below);
- a query that blows its SLO budget while an absorb is in flight
  captures the absorbing site by name plus its own span chain;
- ``profile_NNN.json`` numbers like the flight files and lands atomic.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from tse1m_tpu.observability import flight, profiling
from tse1m_tpu.observability.metrics import histogram, reset_metrics
from tse1m_tpu.observability.tracing import span, thread_span_chain
from tse1m_tpu.resilience.watchdog import deadline_clock
from tse1m_tpu.trace import sync as tsync


@pytest.fixture(autouse=True)
def _clean_profiling_plane():
    """Every test starts with no sampler, no recorder, an empty slowlog
    and a fresh registry — and leaves the plane the same way."""
    profiling.set_profiling(None)
    profiling.stop_sampler()
    profiling.enable_lock_wait(False)
    profiling.slow_request_log().clear()
    reset_metrics()
    yield
    profiling.set_profiling(None)
    profiling.stop_sampler()
    profiling.enable_lock_wait(False)
    profiling.slow_request_log().clear()
    reset_metrics()


def _sampler_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("tse1m-prof-sampler")]


def _burn(seconds: float) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < seconds:
        sum(i * i for i in range(500))


# -- sampler lifecycle + kill switch ------------------------------------------

def test_sampler_start_stop_and_snapshot():
    s = profiling.start_sampler(hz=200.0)
    assert s is not None and _sampler_threads()
    with span("prof.test.burn"):
        _burn(0.15)
    snap = s.snapshot()
    assert snap["samples"] > 0
    assert snap["hz"] == 200.0
    assert snap["plane_self"], snap
    assert "prof.test.burn" in snap["span_self"], snap["span_self"]
    profiling.stop_sampler()
    assert not _sampler_threads()


def test_start_sampler_is_idempotent():
    a = profiling.start_sampler(hz=200.0)
    b = profiling.start_sampler()
    assert a is b
    assert len(_sampler_threads()) == 1


def test_kill_switch_env_refuses_start(monkeypatch):
    monkeypatch.setenv("TSE1M_PROFILING", "0")
    assert profiling.profiling_enabled() is False
    assert profiling.start_sampler() is None
    assert not _sampler_threads()
    assert profiling.enable_lock_wait(True) is False


def test_kill_switch_tears_down_live_sampler():
    assert profiling.start_sampler(hz=200.0) is not None
    profiling.enable_lock_wait(True)
    assert _sampler_threads()
    profiling.set_profiling(False)
    # "off" must mean no sampling threads exist: stop_sampler joined it
    assert not _sampler_threads()
    # ...and the lock-wait recorder detached (raw acquires from here on)
    lk = tsync.Lock("prof.test.dead")
    with lk:
        pass
    assert not any(r["site"] == "prof.test.dead"
                   for r in profiling.lock_wait_summary())
    # env verdict restored by the autouse fixture via set_profiling(None)


def test_env_kill_switch_exits_running_loop(monkeypatch):
    s = profiling.start_sampler(hz=200.0)
    assert s is not None
    monkeypatch.setenv("TSE1M_PROFILING", "0")
    # the loop re-checks the switch every period (5 ms at 200 Hz)
    deadline = time.monotonic() + 2.0
    while _sampler_threads() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not _sampler_threads()


# -- lock-wait attribution ----------------------------------------------------

def _contend(site: str) -> None:
    """Make the calling thread measurably queue on a lock named
    ``site`` while the recorder watches."""
    lk = tsync.Lock(site)
    held = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            held.set()
            release.wait(2.0)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert held.wait(2.0)
    threading.Timer(0.03, release.set).start()
    with lk:
        pass
    t.join(2.0)


def test_lock_wait_histograms_per_site():
    profiling.enable_lock_wait(True)
    _contend("prof.test.contended")
    rows = {r["site"]: r for r in profiling.lock_wait_summary()}
    assert "prof.test.contended" in rows, rows
    assert rows["prof.test.contended"]["count"] >= 1
    assert rows["prof.test.contended"]["max_ms"] >= 10.0


def test_lock_wait_recorder_survives_registry_locks():
    """The deadlock regression: recording a wait for the registry's OWN
    lock must not re-acquire it (the pending-buffer design)."""
    profiling.enable_lock_wait(True)
    done = []

    def worker():
        for i in range(200):
            histogram("prof_test_regress", lane=str(i % 3)).observe(0.001)
        done.append(True)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert len(done) == 4, "registry traffic deadlocked under recorder"
    assert any(r["site"] == "MetricsRegistry"
               for r in profiling.lock_wait_summary())


def test_drain_lock_waits_is_per_thread_and_one_shot():
    profiling.enable_lock_wait(True)
    _contend("prof.test.drain")
    waits = profiling.drain_lock_waits()
    assert any(site == "prof.test.drain" for site, _ in waits), waits
    assert profiling.drain_lock_waits() == []  # drained


# -- slow-request capture -----------------------------------------------------

def test_capture_slow_request_names_absorbing_site():
    profiling.start_sampler(hz=200.0)
    with span("serve.query.test"):
        time.sleep(0.02)
        rec = profiling.capture_slow_request(
            "query", wall_s=0.02, budget_ms=1.0,
            absorb={"site": "serve.index.swap", "rows": 4096,
                    "since_s": 1.0},
            rows=1)
    assert rec["kind"] == "query"
    assert rec["wall_ms"] == pytest.approx(20.0)
    assert rec["absorb"]["site"] == "serve.index.swap"
    assert rec["absorb"]["rows"] == 4096
    # the capture ran inside the open span: the chain names it
    assert "serve.query.test" in rec["span_chain"], rec["span_chain"]
    assert rec["tags"]["rows"] == 1
    assert profiling.slow_requests_total() == 1
    assert profiling.recent_slow_requests()[-1]["kind"] == "query"


def test_thread_span_chain_mirrors_nesting():
    with span("outer"):
        with span("inner"):
            chain = thread_span_chain()
    assert chain[-2:] == ["outer", "inner"]
    assert thread_span_chain() == []  # both closed


def test_slowlog_ring_is_bounded():
    slog = profiling.SlowRequestLog(capacity=4)
    for i in range(10):
        slog.append({"kind": "query", "i": i})
    assert slog.total() == 10
    assert [r["i"] for r in slog.recent()] == [6, 7, 8, 9]
    assert [r["i"] for r in slog.recent(2)] == [8, 9]


def test_daemon_query_slow_capture_behind_absorb(tmp_path):
    """The acceptance shape: a query that blows its budget while the
    daemon is mid-absorb captures the absorbing site by name, with the
    query's span chain attached."""
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.serve import ServeDaemon, SloPolicy

    items = synth_session_sets(64, set_size=64, seed=5)[0]
    dm = ServeDaemon(str(tmp_path / "store"),
                     params=ClusterParams(n_hashes=32, n_bands=4,
                                          use_pallas="never"),
                     slo=SloPolicy(query_p99_target_ms=0.0)).start()
    try:
        dm.ingest(items, timeout=60)
        dm.quiesce(timeout=60)
        # Freeze a mid-absorb state the way the ingest thread publishes
        # it (GIL-atomic whole-dict overwrite), then query: a 0 ms
        # budget makes every query an SLO violation, so the capture is
        # deterministic.
        dm._busy = True
        dm._inflight = {"site": "serve.index.swap", "rows": 4096,
                        "since_s": 0.0}
        with span("serve.query"):
            dm.query(items[:1])
    finally:
        dm._busy = False
        dm.stop(commit=False)
    assert profiling.slow_requests_total() >= 1
    rec = profiling.recent_slow_requests()[-1]
    assert rec["kind"] == "query"
    assert rec["absorb"]["site"] == "serve.index.swap"
    assert rec["budget_ms"] == 0.0
    assert "serve.query" in rec["span_chain"], rec["span_chain"]


# -- profile artifact ---------------------------------------------------------

def test_dump_profile_numbers_like_flight_files(tmp_path):
    profiling.start_sampler(hz=200.0)
    time.sleep(0.05)
    p0 = profiling.dump_profile(d=str(tmp_path))
    p1 = profiling.dump_profile(d=str(tmp_path))
    assert p0.endswith("profile_000.json")
    assert p1.endswith("profile_001.json")
    with open(p0) as f:
        payload = json.load(f)
    for key in ("pid", "uptime_s", "profiling_enabled", "sampler",
                "collapsed_stacks", "lock_wait_sites", "slow_requests",
                "slow_requests_total"):
        assert key in payload, key
    assert payload["sampler"]["hz"] == 200.0
    # atomicity: no temp droppings next to the artifacts
    leftovers = [f for f in os.listdir(str(tmp_path))
                 if not f.startswith("profile_")]
    assert leftovers == [], leftovers


def test_dump_profile_without_directory_is_none(monkeypatch):
    monkeypatch.delenv("TSE1M_FLIGHT_DIR", raising=False)
    monkeypatch.setattr(flight, "_flight_dir", None)
    assert profiling.dump_profile() is None


def test_profile_status_shape():
    profiling.start_sampler(hz=200.0)
    st = profiling.profile_status()
    assert st["profiling_enabled"] is True
    assert st["sampler_alive"] is True
    assert isinstance(st["lock_wait_top"], list)
    assert st["slow_requests_total"] == 0
    profiling.stop_sampler()
    assert profiling.profile_status()["sampler_alive"] is False


def test_sampler_stacks_between_window():
    s = profiling.start_sampler(hz=200.0)
    assert s is not None
    t0 = deadline_clock()
    with span("prof.window.test"):
        _burn(0.1)
    t1 = deadline_clock()
    win = s.stacks_between(t0, t1)
    assert win, "no samples landed in a 100 ms busy window at 200 Hz"
    assert all(t0 - 0.01 <= w["t_s"] <= t1 + 0.01 for w in win)
    assert any(w["span"] == "prof.window.test" for w in win), win[:3]


def test_collapsed_stack_format():
    s = profiling.start_sampler(hz=200.0)
    _burn(0.08)
    np.sort(np.random.default_rng(0).integers(0, 100, 1000))
    lines = s.collapsed(limit=10)
    assert lines and len(lines) <= 10
    stack, count = lines[0].rsplit(" ", 1)
    assert int(count) >= 1
    assert ":" in stack  # frame labels are file:function


# -- serve verbs --------------------------------------------------------------

def test_serve_slowlog_and_profile_verbs(tmp_path):
    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.serve import (ServeClient, ServeDaemon, ServeServer,
                                 SloPolicy)

    flight.set_flight_dir(str(tmp_path / "flight"))
    items = synth_session_sets(64, set_size=64, seed=7)[0]
    dm = ServeDaemon(str(tmp_path / "store"),
                     params=ClusterParams(n_hashes=32, n_bands=4,
                                          use_pallas="never"),
                     slo=SloPolicy(query_p99_target_ms=0.0)).start()
    server = ServeServer(dm, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        profiling.start_sampler(hz=200.0)
        with ServeClient(port=server.port) as c:
            c.ingest(items, timeout_s=60)
            c.quiesce(timeout_s=60)
            q = c.query(items[:4], timeout_s=60)
            assert q["known"].all()
            # budget 0 ms: that query IS a slow request — and it ran
            # inside the server's serve.query span, so the capture's
            # span chain names the op
            sl = c.slowlog()
            assert sl["ok"] and sl["slow_requests_total"] >= 1
            assert sl["slow_requests"][-1]["kind"] == "query"
            assert "serve.query" in sl["slow_requests"][-1]["span_chain"]
            assert len(c.slowlog(n=1)["slow_requests"]) == 1
            # status surfaces the graftprof counters
            st = c.status()
            assert st["slow_requests_total"] >= 1
            assert isinstance(st["lock_wait_top"], list)
            assert len(st["lock_wait_top"]) <= 3
            # profile verb: live summary + dumped artifact on demand
            pr = c.profile()
            assert pr["ok"] and pr["profiling_enabled"] is True
            assert pr["sampler_alive"] is True
            pr2 = c.profile(dump=True)
            assert pr2["profile_path"].endswith("profile_000.json")
            with open(pr2["profile_path"]) as f:
                assert json.load(f)["pid"] == os.getpid()
            c.shutdown()
    finally:
        flight.set_flight_dir(None)
        server.server_close()
        dm.stop(commit=False)


def test_cli_serve_client_lists_new_ops(capsys):
    from tse1m_tpu import cli as _cli

    with pytest.raises(SystemExit) as ei:
        _cli.main(["serve-client", "--help"])
    assert ei.value.code == 0
    out = capsys.readouterr().out
    assert "slowlog" in out and "profile" in out
    with pytest.raises(SystemExit):
        _cli.main(["serve-client", "not-an-op"])
