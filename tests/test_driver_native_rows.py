"""Driver-native row decode (the psycopg2 shape, offline).

A live Postgres returns rows that look nothing like sqlite's text rows:
TIMESTAMPTZ -> tz-aware ``datetime``, DATE -> ``datetime.date``, TEXT[] ->
Python ``list``.  The columnar extractor must decode BOTH shapes to
identical arrays (``to_epoch_ns``'s mixed/utc ladder, ``parse_array``'s
list branch).  ``tests/test_postgres_live.py`` proves this against a real
server; this offline twin serves the SAME study through a wrapper that
converts sqlite rows into psycopg2's exact shapes, so the decode ladder is
pinned in environments without a server (like this one's CI).
"""

from __future__ import annotations

import datetime as dt
import json

import numpy as np
import pytest

from tse1m_tpu.config import Config
from tse1m_tpu.data.columnar import StudyArrays
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.db.connection import DB
from tse1m_tpu.data.synth import SynthSpec, generate_study

UTC = dt.timezone.utc


def _ts(text: str) -> dt.datetime:
    """sqlite text timestamp -> what psycopg2 yields for TIMESTAMPTZ."""
    return dt.datetime.fromisoformat(str(text).replace("T", " ")).replace(
        tzinfo=UTC)


def _date(text) -> dt.date:
    s = str(text)[:10]
    return dt.date.fromisoformat(s)


def _arr(text) -> list:
    return [] if text in (None, "") else list(json.loads(text))


class FakePsycopgDB:
    """Serves a sqlite study DB through psycopg2's row shapes.

    dialect='postgres' also forces the extractor off the native sqlite
    decoder, exactly like a real Postgres connection."""

    dialect = "postgres"

    _CONVERTERS = {
        "SELECT project, name, timecreated, result, modules, revisions":
            (None, None, _ts, None, _arr, _arr),
        "SELECT project, timecreated, modules, revisions, result":
            (None, _ts, _arr, _arr, None),
        "SELECT project, number, rts, status, crash_type, severity":
            (None, None, _ts, None, None, None),
        "SELECT project, date, coverage, covered_line, total_line":
            (None, _date, None, None, None),
    }

    def __init__(self, inner: DB):
        self.inner = inner
        self.config = inner.config

    def query(self, sql, params=()):
        rows = self.inner.query(sql, params)
        conv = None
        for prefix, c in self._CONVERTERS.items():
            if sql.startswith(prefix):
                conv = c
                break
        if conv is None:
            # Only the eligibility query (single project column) may pass
            # through unconverted — any other study SELECT slipping
            # through means a stale prefix and psycopg2 shapes silently
            # not exercised (caught live in round 4 when covb dropped its
            # name column).
            assert sql.startswith("SELECT project FROM total_coverage"), \
                f"stale converter prefix for: {sql[:80]}"
            return rows
        return [tuple(v if f is None or v is None else f(v)
                      for f, v in zip(conv, row)) for row in rows]


@pytest.fixture(scope="module")
def dbs(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("drv") / "study.sqlite")
    cfg = Config(engine="sqlite", sqlite_path=path, limit_date="2026-01-01")
    db = DB(config=cfg).connect()
    generate_study(SynthSpec(n_projects=10, days=400, seed=21)).to_db(db)
    yield db, cfg
    db.closeConnection()


def test_driver_native_rows_decode_identically(dbs):
    db, cfg = dbs
    want = StudyArrays.from_db(db, cfg)
    got = StudyArrays.from_db(FakePsycopgDB(db), cfg)
    assert not getattr(got, "native_decode", False)  # pandas ladder carried it
    assert got.projects == want.projects
    for table in ("fuzz", "covb", "issues", "cov"):
        a, b = getattr(got, table), getattr(want, table)
        np.testing.assert_array_equal(a.offsets, b.offsets, err_msg=table)
    np.testing.assert_array_equal(got.fuzz.columns["time_ns"],
                                  want.fuzz.columns["time_ns"])
    np.testing.assert_array_equal(got.covb.columns["time_ns"],
                                  want.covb.columns["time_ns"])
    np.testing.assert_array_equal(got.issues.columns["time_ns"],
                                  want.issues.columns["time_ns"])
    np.testing.assert_array_equal(got.cov.columns["date_ns"],
                                  want.cov.columns["date_ns"])
    np.testing.assert_array_equal(got.fuzz.columns["ok"],
                                  want.fuzz.columns["ok"])
    for col in ("coverage", "covered", "total"):
        np.testing.assert_array_equal(got.cov.columns[col],
                                      want.cov.columns[col], err_msg=col)
    # grouphash codes come from different raw forms (list vs json text);
    # the grouping PATTERN must be identical.
    ga, gb = got.covb.columns["grouphash"], want.covb.columns["grouphash"]
    np.testing.assert_array_equal(ga[1:] == ga[:-1], gb[1:] == gb[:-1])


def test_rq_results_identical_through_driver_native_rows(dbs):
    db, cfg = dbs
    limit_ns = int(np.datetime64(cfg.limit_date, "ns").astype(np.int64))
    be = PandasBackend()
    want = StudyArrays.from_db(db, cfg)
    got = StudyArrays.from_db(FakePsycopgDB(db), cfg)
    a = be.rq1_detection(got, limit_ns, min_projects=1)
    b = be.rq1_detection(want, limit_ns, min_projects=1)
    for f in ("iterations", "total_projects", "detected_counts", "link_idx"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f),
                                      err_msg=f)
    # rq3 drives parse_array + rev_hash over the list-shaped raw columns.
    r3a = be.rq3_coverage_at_detection(got, limit_ns)
    r3b = be.rq3_coverage_at_detection(want, limit_ns)
    np.testing.assert_array_equal(r3a.det_issue_idx, r3b.det_issue_idx)
    np.testing.assert_array_equal(r3a.det_diff_percent, r3b.det_diff_percent)
