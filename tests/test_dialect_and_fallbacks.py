"""Paths CI otherwise never executes: the Postgres dialect surface (this
image has no psycopg2 and no server, so every other test runs sqlite) and
the native decoder's degrade-to-pandas ladder.

The reference runs exclusively against Postgres (dbFile.py:27,
docker-compose.yml:10-20); a drop-in rebuild must keep that dialect's SQL
adaptation and DDL correct even though CI exercises sqlite, so these tests
pin the translation layer itself.
"""

from __future__ import annotations

import numpy as np
import pytest

from tse1m_tpu.config import Config
from tse1m_tpu.db import schema
from tse1m_tpu.db.connection import DB


# -- postgres dialect surface (no server needed) ------------------------------

def test_qmark_adaptation_for_postgres():
    db = DB.__new__(DB)  # no connection: exercise _adapt in isolation
    db.dialect = "postgres"
    assert db._adapt("SELECT * FROM t WHERE a = ? AND b IN (?, ?)") == \
        "SELECT * FROM t WHERE a = %s AND b IN (%s, %s)"
    db.dialect = "sqlite"
    assert db._adapt("SELECT ?") == "SELECT ?"


def test_postgres_ddl_differs_where_it_must():
    pg = schema.ddl("postgres")
    lite = schema.ddl("sqlite")
    # Same table set either way.
    for table in ("issues", "buildlog_data", "total_coverage",
                  "project_info", "projects"):
        assert table in pg and table in lite
    # Engine-specific column typing: timestamptz is a Postgres type.
    assert "timestamptz" in pg.lower()
    assert "timestamptz" not in lite.lower()


def test_postgres_without_driver_falls_back_to_sqlite(tmp_path, monkeypatch):
    from tse1m_tpu.db import pglib

    cfg = Config(engine="postgres",
                 sqlite_path=str(tmp_path / "fallback.sqlite"))
    # Simulate a box with neither psycopg2 nor libpq: the wrapper must
    # degrade to sqlite rather than fail at import time (Config keeps the
    # requested engine; only the resolved dialect changes).
    monkeypatch.setattr(pglib, "available", lambda: False)
    db = DB(config=cfg)
    assert db.dialect == "sqlite"
    db.connect()
    db.execute("CREATE TABLE t (x INTEGER)")
    db.execute("INSERT INTO t VALUES (?)", (3,))
    assert db.query("SELECT x FROM t", ()) == [(3,)]
    db.closeConnection()


def test_postgres_resolves_to_pglib_without_psycopg2(tmp_path):
    """With libpq present (this image) and psycopg2 absent, engine=postgres
    resolves to the ctypes driver instead of silently degrading."""
    from tse1m_tpu.db import pglib

    try:
        import psycopg2  # noqa: F401

        pytest.skip("psycopg2 present; resolution prefers it")
    except ImportError:
        pass
    if not pglib.available():
        pytest.skip("libpq not present")
    db = DB(config=Config(engine="postgres"))
    assert db.dialect == "postgres"
    assert db._pg_driver == "pglib"


# -- native decoder degrade ladder -------------------------------------------

def test_native_loader_degrades_without_compiler(monkeypatch, tmp_path):
    """No g++ / failed compile must yield fetch_table() -> None (pandas
    fallback), never an exception at import or call time."""
    from tse1m_tpu import native

    monkeypatch.setattr(native, "_module", None)
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setattr(native, "_build_and_load", lambda *a, **k: None)
    assert native.fetch_table("/nope.sqlite", "SELECT 1", (), "o", []) is None
    # ...and the delta-grouping extension degrades the same way
    monkeypatch.setattr(native, "_enc_module", None)
    monkeypatch.setattr(native, "_enc_tried", False)
    assert native.group_delta_native(
        np.zeros((2, 2), np.uint32), 4, 1) is None


def test_columnar_works_end_to_end_without_native(study_db, study_cfg,
                                                  monkeypatch):
    from tse1m_tpu.data import columnar
    from tse1m_tpu.data.columnar import StudyArrays

    monkeypatch.setattr(columnar, "_native_db_path", lambda _db: None)
    arrays = StudyArrays.from_db(study_db, study_cfg)
    assert arrays.n_projects > 0
    assert not arrays.native_decode
    assert arrays.fuzz.offsets[-1] == len(arrays.fuzz)
    assert arrays.fuzz.columns["time_ns"].dtype == np.int64
