"""Final-path write-mode open: a crash mid-write leaves a torn file."""
import json


def save(path, payload):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
