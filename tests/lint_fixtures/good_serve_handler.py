"""broad-except fixture (GOOD, serve request handler): errors become
structured responses, but injected faults re-raise through the handler
(fault transparency — the serve plane's request handlers follow the
same discipline as every other production seat)."""
from tse1m_tpu.resilience import reraise_if_fault


def handle_request(daemon, msg):
    try:
        return {"ok": True, "labels": daemon.query(msg["vectors"])}
    except Exception as e:
        reraise_if_fault(e)
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}
