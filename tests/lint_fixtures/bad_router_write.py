"""BAD (spoofed tse1m_tpu/serve/router.py): the router touches the
write plane — a store handle, a store mutator, spilled state."""

from tse1m_tpu.cluster.store import SignatureStore


def forward_and_spill(store_dir, rows, acks):
    store = SignatureStore(store_dir, {})
    store.append(rows, rows)
    with open(store_dir + "/router_state.json", "w") as f:
        f.write("{}")
    return acks
