"""Span discipline done right: contexts close, escape hatch finalizes."""
import contextlib

from tse1m_tpu.observability import tracing
from tse1m_tpu.observability.tracing import span, start_span


def good_with(work):
    with span("work", kind="demo"):
        work()


def good_with_alias(work):
    with tracing.span("work") as sp:
        sp.set_tag("rows", 3)
        work()


def good_enter_context(work):
    with contextlib.ExitStack() as stack:
        stack.enter_context(span("work"))
        work()


def good_manual_finalized(work):
    sp = start_span("work")
    try:
        work()
    finally:
        sp.end(ok=True)
