"""Lease lifecycle through the blessed seats: atomic writes only, epochs
instead of clocks, reads are plain opens."""
import json

from tse1m_tpu.utils.atomic import atomic_write


def write_lease_atomic(path, epoch, owner, nonce):
    # the one blessed mutation shape (resilience.coordinator.write_lease)
    with atomic_write(path) as f:
        json.dump({"epoch": epoch, "owner": owner, "nonce": nonce}, f)


def read_lease_plain(path):
    # reads never mutate; a read-mode open is out of scope
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def unrelated_report_writer(path, payload):
    # no lease/heartbeat semantics in the name: out of this rule's scope
    with open(path, "w") as f:
        json.dump(payload, f)
