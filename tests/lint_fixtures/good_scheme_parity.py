"""Fixture: signature computation dispatched through the scheme registry."""
import numpy as np

from tse1m_tpu.cluster.schemes import (make_params, scheme_host_signatures,
                                       scheme_sig_and_keys)


def ingest(rows, scheme, n_hashes, seed, n_bands):
    hp = make_params(scheme, n_hashes, seed)
    sig, keys = scheme_sig_and_keys(rows, hp.device(), n_bands)
    host = scheme_host_signatures(np.asarray(rows), hp)
    return sig, keys, host
