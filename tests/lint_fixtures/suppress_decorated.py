"""Regression fixture: a standalone suppression directly above a
DECORATED def must cover the whole decorator span (including multi-line
decorator continuation lines) and the ``def`` line itself."""

import functools

import jax


def probe(const):
    def deco(fn):
        return fn
    return deco


# graftlint: disable=wire-layer -- fixture: pinned probe constant rides the decorator
@probe(
    jax.device_put([1]))
def suppressed(x):
    return x


@probe(
    jax.device_put([2]))
def unsuppressed(x):
    return x
