"""Every shared mutation under the lock (or no lock declared at all)."""
import threading


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0.0

    def add(self, x):
        with self._lock:
            self.total += x

    def reset(self):
        with self._lock:
            self.total = 0.0


class PlainCounter:  # no lock: single-threaded by design, out of scope
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
