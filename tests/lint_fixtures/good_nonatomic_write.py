"""tmp+rename (inline or via utils.atomic) and read modes all pass."""
import json
import os

from tse1m_tpu.utils.atomic import atomic_write


def save_inline(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def save_helper(path, payload):
    with atomic_write(path) as f:
        json.dump(payload, f)


def load(path):
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)
