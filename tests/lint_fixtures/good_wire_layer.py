"""No raw transfers: out-of-plane code feeds the blessed wire layer
(here the scoring plane's streaming entry point) instead of opening its
own host<->device link."""
from tse1m_tpu.cluster.kernels.score import bulk_topk_store


def rank(store, query_sigs, k):
    return bulk_topk_store(store, query_sigs, k)
