"""Lock-owning class mutating shared state outside its lock."""
import threading


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0.0

    def add(self, x):
        with self._lock:
            self.total += x

    def reset(self):
        self.total = 0.0


_lock = threading.Lock()
_shared = None


def set_shared(v):
    global _shared
    with _lock:
        _shared = v


def clear_shared():
    global _shared
    _shared = None
