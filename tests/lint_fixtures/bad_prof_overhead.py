"""prof-overhead fixture: a profiler that can outlive its process."""
import threading


class Sampler:
    def start(self):
        # no daemon flag at all: blocks interpreter exit
        t = threading.Thread(target=self._loop, name="sampler")
        t.start()
        return t

    def _loop(self):
        pass


def start_profiler(fn, live):
    # computed daemon flag: an unauditable maybe, same finding
    t = threading.Thread(target=fn, daemon=bool(live))
    t.start()
    return t
