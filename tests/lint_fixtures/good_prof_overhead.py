"""prof-overhead fixture: daemon threads + the kill switch consulted."""
import os
import threading


def profiling_enabled():
    return os.environ.get("TSE1M_PROFILING", "1") != "0"


class Sampler:
    def start(self):
        if not profiling_enabled():
            return None
        t = threading.Thread(target=self._loop, daemon=True,
                             name="sampler")
        t.start()
        return t

    def _loop(self):
        pass


def start_profiler(fn):
    if not profiling_enabled():
        return None
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
