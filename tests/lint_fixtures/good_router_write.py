"""GOOD (spoofed tse1m_tpu/serve/router.py): stateless fan-out — the
router READS the owner's port file, forwards, and maps acks in memory;
its own port file goes through atomic_write."""

from tse1m_tpu.utils.atomic import atomic_write


def forward(transport, msg, port_file):
    with open(port_file, encoding="utf-8") as f:
        port = int(f.read().strip())
    return transport(dict(msg, port=port))


def publish_port(port_file, port):
    with atomic_write(port_file) as f:
        f.write(str(port))
