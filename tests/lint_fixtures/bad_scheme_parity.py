"""Fixture: raw signature-kernel calls outside the scheme registry."""
import numpy as np

from tse1m_tpu.cluster.host import host_signatures
from tse1m_tpu.cluster.minhash import minhash_signatures
from tse1m_tpu.cluster.minhash_pallas import cminhash_and_keys, minhash_and_keys


def ingest(rows, a, b):
    # BAD: hard-codes the kminhash family — a cminhash/weighted run
    # would silently verify against the wrong kernel.
    sig = minhash_signatures(rows, a, b)
    host = host_signatures(np.asarray(rows), a, b)
    return sig, host


def fused(rows, a, b, n_bands):
    return minhash_and_keys(rows, a, b, n_bands)


def fused_cm(rows, consts, n_bands):
    return cminhash_and_keys(rows, *consts, n_bands)
