"""Watchdog-plane code reading time through the blessed helper."""
import time

from tse1m_tpu.resilience.watchdog import deadline_clock


def deadline_clock_local():  # not THE helper, but calls no raw clock
    return deadline_clock()


def arm_deadline(budget_s):
    return deadline_clock() + budget_s


def unrelated_telemetry():
    # no deadline/watchdog/stall semantics in the name: out of scope
    return time.perf_counter()
