"""Seeded RNG, monotonic clocks, caller-passed dates all pass."""
import random
import time


_RNG = random.Random(0)


def jittery_wait():
    time.sleep(_RNG.uniform(0.0, 0.1))


def elapsed(t0):
    return time.monotonic() - t0
