"""Narrow, guarded, chained, or suppressed handlers all pass."""
from tse1m_tpu.resilience import InjectedFault, reraise_if_fault


def narrow(path):
    try:
        return open(path).read()
    except (OSError, ValueError):
        return None


def guarded(fn):
    try:
        return fn()
    except Exception as e:
        reraise_if_fault(e)
        return None


def isinstance_guard(fn):
    try:
        return fn()
    except Exception as e:
        if isinstance(e, InjectedFault):
            raise
        return None


def chained(fn):
    try:
        return fn()
    except Exception as e:
        raise RuntimeError("wrapped") from e


def suppressed(fn):
    try:
        return fn()
    except Exception:  # graftlint: disable=broad-except -- fixture reason
        return None
