"""Direct HTTP / raw cursor calls skipping the retry engine."""
import requests


def fetch(url):
    return requests.get(url, timeout=5)


def raw_sql(db, sql):
    return db.cursor.execute(sql)
