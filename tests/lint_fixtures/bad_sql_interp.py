"""Raw identifier/value interpolation into SQL text."""


def count_rows(db, table):
    return db.query(f"SELECT COUNT(*) FROM {table}")


def fmt(table):
    return "DELETE FROM {}".format(table)


def percent(table):
    return "DROP TABLE %s" % table
