"""jnp ops, static-arg control flow, and untraced helpers all pass."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("bits",))
def unpack(x, bits):
    if bits % 8 == 0:
        return x >> jnp.uint32(bits)
    return x & jnp.uint32((1 << bits) - 1)


@jax.jit
def pure_device(x):
    return jnp.where(x > 0, x, -x)


def host_helper(x):
    return float(np.asarray(x)[0])
