"""Lease mutations that bypass the atomic-write helper or stamp
wall-clock time (spoofed into the watchdog plane's name scope)."""
import json
import time


def write_lease_direct(path, epoch, owner):
    # BAD: a raw writable open can leave a TORN lease a reader
    # misparses as absent — two writers could then hold one range.
    with open(path, "w") as f:
        json.dump({"epoch": epoch, "owner": owner}, f)


def renew_lease_stamped(path, epoch):
    # BAD x2: nonatomic write AND a wall-clock stamp (clocks are not
    # comparable across hosts; fencing is by epoch only).
    rec = {"epoch": epoch, "ts": time.time()}
    with open(path, mode="w") as f:
        json.dump(rec, f)


def heartbeat_flush(path, seq):
    # BAD: heartbeat files share the lease plane's atomicity contract.
    with open(path, "a") as f:
        f.write(str(seq))
