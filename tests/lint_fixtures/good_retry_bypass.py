"""I/O through the blessed layers passes."""
from tse1m_tpu.collect.transport import FetchPolicy, HttpFetcher


def fetch(url):
    return HttpFetcher(FetchPolicy()).get(url)


def through_db(db, sql, params):
    return db.query(sql, params)
