"""watchdog-clock fixture (BAD, serve plane): raw clocks in SLO /
admission code fork the time base the query p99 is measured on, and a
raw clock in ANY file under tse1m_tpu/serve/ is in scope."""
import time


def admission_window_open(depth):
    # BAD: admission decisions must share the watchdog's monotonic base
    return time.monotonic() if depth else 0.0


def query_slo_wall():
    return time.perf_counter()  # BAD: slo-marked name, raw clock
