"""Wall clock + global RNG in a chaos-replayed plane (spoofed path)."""
import random
import time
from datetime import datetime


def jittery_wait():
    time.sleep(random.uniform(0.0, 0.1))


def stamp():
    return time.time(), datetime.now()
