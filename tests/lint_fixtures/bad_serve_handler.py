"""broad-except fixture (BAD, serve request handler): a handler that
turns EVERY failure into an error response also swallows injected
faults — a chaos run then sees a cosmetic error string instead of the
real failure mode."""


def handle_request(daemon, msg):
    try:
        return {"ok": True, "labels": daemon.query(msg["vectors"])}
    except Exception as e:  # BAD: InjectedFault becomes a JSON string
        return {"ok": False, "error": str(e)}
