"""Swallows everything — including resilience.InjectedFault."""


def read_batch(path):
    try:
        return open(path).read()
    except Exception:
        return None


def scan(paths):
    for p in paths:
        try:
            yield open(p).read()
        except:  # noqa: E722
            continue
