"""Leaky tracing spans: escape the context-manager discipline."""
from tse1m_tpu.observability.tracing import span, start_span


def bad_inline_span(work):
    sp = span("work")  # never entered: the span object just leaks
    work()
    return sp


def bad_manual_no_finally(work):
    sp = start_span("work")
    work()  # an exception here leaves the span open forever
    sp.end()


def bad_span_as_argument(record, work):
    record(span("work"))  # handed off, nothing guarantees a close
    work()
