"""Host ops and traced-value control flow inside jit bodies."""
import jax
import numpy as np


@jax.jit
def uses_numpy(x):
    return x + np.float32(1.0)


@jax.jit
def syncs(x):
    return float(x[0]) + x.sum().item()


@jax.jit
def branches(x):
    if x > 0:
        return x
    return -x
