"""GOOD (spoofed tse1m_tpu/serve/replicate.py): read-only handle,
adoption only via refresh()/__init__/_rebuild, stream writes its frames
but never the adopted generation."""

import shutil

from tse1m_tpu.cluster.store import SignatureStore


class Replica:
    def __init__(self, directory):
        self.store = SignatureStore(directory, {}, read_only=True)
        self._generation_adopted = -1
        self._rebuild()

    def _rebuild(self):
        self._generation_adopted = int(self.store.generation)

    def refresh(self):
        if self.store.refresh():
            self._rebuild()
            return True
        return False

    def query(self, rows):
        return self.store.load_signatures(rows, rows)


def stream(src, dst):
    shutil.copyfile(src, dst + ".tmp")
