"""watchdog-clock fixture (GOOD, serve plane): every SLO/admission
timestamp reads through the plane's one monotonic clock."""
from tse1m_tpu.resilience.watchdog import deadline_clock


def admission_window_open(depth):
    return deadline_clock() if depth else 0.0


def query_slo_wall():
    return deadline_clock()


def format_request(payload):
    # names without deadline/slo/admission markers are out of scope in
    # ordinary files (the whole-file rule only binds inside the plane)
    return dict(payload)
