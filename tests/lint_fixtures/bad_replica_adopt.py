"""BAD (spoofed tse1m_tpu/serve/replicate.py): a replica that joins the
write plane — writable store handle, adoption outside refresh(), a
store mutator."""

from tse1m_tpu.cluster.store import SignatureStore


class Replica:
    def __init__(self, directory):
        self.store = SignatureStore(directory, {})
        self._generation_adopted = -1

    def query(self, rows):
        self._generation_adopted = int(self.store.generation)
        self._rebuild()
        return rows

    def _rebuild(self):
        pass

    def trim(self):
        self.store.evict(0)
