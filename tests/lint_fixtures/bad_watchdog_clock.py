"""Raw clock reads in watchdog-plane code (spoofed path)."""
import time


def arm_deadline(budget_s):
    return time.monotonic() + budget_s


def watchdog_tick():
    return time.perf_counter()


def stall_elapsed(t0):
    return time.time() - t0
