"""device_put outside the blessed wire layer (spoofed path)."""
import jax


def stage(x):
    return jax.device_put(x)


def fetch(x):
    return jax.device_get(x)
