"""Fixture: builds fresh arrays and publishes by one reference swap."""
import numpy as np

from .index import Snap


class Serve:
    __publish_slots__ = ("_snap",)

    def __init__(self) -> None:
        self._snap = Snap(0, np.zeros(4, np.int64))

    def absorb(self, row: int, lab: int) -> None:
        labels = self._snap.labels.copy()   # private copy, mutate freely
        labels[row] = lab
        self._snap = Snap(self._snap.generation + 1, labels)  # ONE swap
