"""Fixture: the same snapshot type, used with the publish discipline."""
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Snap:
    generation: int
    labels: np.ndarray
