"""GOOD twin: the supervision path lets the fence signal escape — a
bare ``raise`` relays it, and an explicit LeaseSupersededError handler
is deliberate handling, not absorption."""

from .coordinator import LeaseSupersededError
from .store import ShardedSignatureStore


def supervise(rows):
    st = ShardedSignatureStore("/tmp/x")
    try:
        return st.append(rows)
    except Exception:
        raise  # the fence signal propagates verbatim


def supervise_handled(rows):
    st = ShardedSignatureStore("/tmp/x")
    try:
        return st.append(rows)
    except LeaseSupersededError:
        return None  # deliberate: demoted to read-only upstream
