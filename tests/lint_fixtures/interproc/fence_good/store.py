"""GOOD twin: every per-range append is dominated by a lease check."""

from .coordinator import verify_lease


class SignatureStore:
    def __init__(self, root):
        self.root = root

    def append(self, rows):
        return len(rows)


class ShardedSignatureStore:
    def __init__(self, root):
        self.root = root

    def _check_lease(self, r):
        verify_lease(self.root, r)

    def range_store(self, r):
        store = SignatureStore(self.root)
        return store

    def append(self, rows):
        self._check_lease(0)
        return self.range_store(0).append(rows)
