"""GOOD twin: the lease plane with the blessed mutation seats."""

import json
import os


class LeaseSupersededError(RuntimeError):
    pass


def verify_lease(root, range_id):
    raise LeaseSupersededError(range_id)


def atomic_write(path):
    return open(path + ".tmp", "w")


def write_lease(root, range_id, epoch):
    with atomic_write(os.path.join(root, f"lease_{range_id}.json")) as f:
        json.dump({"range": range_id, "epoch": epoch}, f)


class MembershipLedger:
    def __init__(self, pod_dir):
        self.path = os.path.join(pod_dir, "membership.json")

    def _write(self, rec):
        with atomic_write(self.path) as f:
            json.dump(rec, f)

    def advance(self, members):
        self._write({"members": members})
