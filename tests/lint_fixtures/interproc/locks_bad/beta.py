"""BAD: the reverse acquisition order of alpha.py (cycle closes)."""

import threading

from . import alpha


class Monitor:
    def __init__(self):
        self._lock = threading.Lock()

    def poll(self):
        with self._lock:
            pass

    def flush(self):
        with self._lock:
            r = alpha.Recorder()
            r.add()
