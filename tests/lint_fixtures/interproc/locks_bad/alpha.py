"""BAD: takes alpha's lock, then calls into beta which takes beta's
lock — while beta.flush does the reverse.  Cross-module cycle."""

import threading

from . import beta


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()

    def add(self):
        with self._lock:
            m = beta.Monitor()
            m.poll()

    def relock(self):
        # BAD on its own: non-reentrant Lock re-acquired under itself.
        with self._lock:
            with self._lock:
                pass
