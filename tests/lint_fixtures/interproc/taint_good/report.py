"""GOOD twin of taint_bad/report.py: identifiers route through the
db/ident.py helpers and values bind as parameters, so the SQL text
reaching the sink is blessed at every hop."""

from .dbwrap import run_stmt


def quote_ident(name):
    return '"' + str(name).replace('"', '""') + '"'


def daily_report(db, table, day):
    run_stmt(db, f"SELECT * FROM {quote_ident(table)} WHERE day = ?",
             (day,))
    run_stmt(db, "SELECT COUNT(*) FROM builds")
