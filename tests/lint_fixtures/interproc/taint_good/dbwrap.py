"""GOOD twin of taint_bad/dbwrap.py: the helper takes the blessed DB
wrapper (opaque object, no raw cursor capability flows in)."""


def run_stmt(db, sql, params=()):
    return db.execute(sql, params)
