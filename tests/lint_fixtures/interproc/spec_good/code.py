"""GOOD: the module binds itself to the `toy` spec and its one fault
seat is claimed by a spec action."""

SPEC_MODELS = ("toy",)


def fault_point(site, path=None):  # stand-in for resilience.faults
    pass


def do_write(path):
    fault_point("io.write", path=path)


class ServeServer:
    def _dispatch_op(self, op, msg):
        if op == "ping":
            return {"ok": True}
        return {"ok": False}
