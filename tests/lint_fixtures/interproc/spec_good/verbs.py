"""Fixture verb alphabet matching the one dispatch surface here."""

SERVER_VERBS = ("ping",)
