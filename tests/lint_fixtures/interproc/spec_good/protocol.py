"""GOOD: every spec action seat resolves to real code — the fault
seat exists, the verb is dispatched, the call target is defined, and
model: seats are exempt by design."""

SPEC_NAME = "toy"


class Action:  # stand-in for tse1m_tpu.spec.dsl.Action
    def __init__(self, name, guard, effect, seat="model:env",
                 fair=False):
        pass


def build():
    return (
        Action("write", lambda s: True, lambda s: s,
               seat="fault:io.write", fair=True),
        Action("ping", lambda s: True, lambda s: s, seat="verb:ping"),
        Action("flush", lambda s: True, lambda s: s,
               seat="call:do_write"),
        Action("crash", lambda s: True, lambda s: s,
               seat="model:crash"),
    )
