"""GOOD twin: every declared seat has a matrix entry and vice versa."""


def fault_point(site, path=None):  # stand-in for resilience.faults
    pass


def save_shard(path):
    fault_point("store.sig.save", path=path)


def fetch(url, site="http.fetch"):
    fault_point(site)
    return url
