"""Fixture stand-in for resilience/faults.py's kind registry."""

_KINDS = ("raise", "kill", "stall")
