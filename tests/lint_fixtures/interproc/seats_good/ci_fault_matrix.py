"""Fixture matrix inventory matching seats_good/prod.py exactly
(including a seat name resolved through a parameter default)."""

PRODUCTION_SEATS = {
    "store.sig.save": {"kinds": ("kill",), "covered_by": "seat kill"},
    "http.fetch": {"kinds": ("raise", "stall"), "covered_by": "tests"},
}
