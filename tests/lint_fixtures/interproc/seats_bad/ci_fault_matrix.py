"""Fixture matrix inventory with every drift class: a live seat is
covered, one entry is dead, and one lists an unknown fault kind."""

PRODUCTION_SEATS = {
    "store.sig.save": {"kinds": ("kill",), "covered_by": "seat kill"},
    "store.gone.save": {"kinds": ("kill",), "covered_by": "nothing"},
    "store.meteor.save": {"kinds": ("meteor",), "covered_by": "nothing"},
}
