"""BAD: production seats that drifted from the matrix inventory —
``store.extra.save`` has no PRODUCTION_SEATS entry."""


def fault_point(site, path=None):  # stand-in for resilience.faults
    pass


def save_shard(path):
    fault_point("store.sig.save", path=path)


def save_extra(path):
    fault_point("store.extra.save", path=path)
