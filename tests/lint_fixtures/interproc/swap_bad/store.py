"""Fixture: read-modify-write of published references — all must flag."""
from .cache import Run


class Store:
    __publish_slots__ = ("_view", "_runs")

    def __init__(self) -> None:
        self._view = Run()
        self._runs = []

    def push_bad(self, r) -> None:
        self._runs.append(r)      # in-place mutator on the slot
        self._runs += [r]         # augmented write to the slot
        self._view.rows = 5       # store through the published reference

    def push_alias(self, r) -> None:
        runs = self._runs
        runs.append(r)            # same mutation, laundered via an alias

    def swap_two(self, a, b) -> None:
        self._view, self._runs = a, b   # multi-target (non-atomic pair)
