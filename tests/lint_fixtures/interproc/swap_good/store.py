"""Fixture: published references updated only by whole rebinds."""
from .cache import Run


class Store:
    __publish_slots__ = ("_view", "_runs")

    def __init__(self) -> None:
        self._view = Run()
        self._runs = ()

    def push_good(self, r) -> None:
        self._runs = self._runs + (r,)   # rebind: old or new, never mid

    def swap(self, v) -> None:
        self._view = v                   # one reference store
