"""Fixture: the value type behind a published reference."""


class Run:
    def __init__(self) -> None:
        self.rows = 0
