"""BAD: a helper that launders a raw cursor past the per-file rule.

The parameter is named ``c`` (not ``cur``/``cursor``), so the per-file
``retry-bypass`` heuristic cannot see the raw seat; only the
interprocedural cursor-capability pass can — the caller passes a real
``conn.cursor()`` in.  ``sql`` makes this function a SQL sink: whatever
string arrives here is executed verbatim."""


def run_stmt(c, sql):
    c.execute(sql)


def run_many(c, sql, rows):
    c.executemany(sql, rows)
