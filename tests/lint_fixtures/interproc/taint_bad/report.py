"""BAD: interpolated SQL built two calls away from the raw execute.

The f-string itself contains no ``execute`` call, and ``run_stmt``'s
file never interpolates — only the cross-file taint pass connects the
two (sql-interp at the call below, retry-bypass at dbwrap's seat)."""

from .dbwrap import run_stmt


def daily_report(conn, table):
    cur = conn.cursor()
    run_stmt(cur, f"SELECT * FROM {table}")
    return cur
