"""BAD: the server dispatch table drifted both ways — it handles an
undeclared verb (`evict`) and dropped a declared one (`query`)."""


class ServeServer:
    def _dispatch_op(self, op, msg):
        if op == "ping":
            return {"ok": True}
        if op == "evict":
            return {"ok": True, "evicted": 1}
        return {"ok": False}
