"""BAD: the client lost its `query` method — the surface no longer
covers CLIENT_VERBS."""


class ServeClient:
    def request(self, op, **kw):
        return {"op": op, **kw}

    def ping(self):
        return self.request("ping")
