"""Fixture spec verb alphabets the surfaces here drift from."""

SERVER_VERBS = ("ping", "query")
ROUTER_VERBS = ("ping",)
CLIENT_VERBS = ("ping", "query")
FORWARD_VERBS = ("ping",)
