"""BAD: the in-process forwarder speaks a verb (`status`) the
FORWARD_VERBS alphabet never declared."""


class RouterServer:
    def _dispatch_op(self, op, msg):
        if op == "ping":
            return {"ok": True}
        return {"ok": False}


class LocalTransport:
    def __call__(self, msg):
        op = str(msg.get("op", ""))
        if op == "ping":
            return {"ok": True}
        if op == "status":
            return {"ok": True, "rows": 0}
        return {"ok": False}
