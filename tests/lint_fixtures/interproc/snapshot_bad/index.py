"""Fixture: a published snapshot type (immutable-after-publish)."""
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Snap:
    generation: int
    labels: np.ndarray
