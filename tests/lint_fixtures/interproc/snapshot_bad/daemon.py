"""Fixture: mutates published snapshots — every seat must flag."""
import numpy as np

from .index import Snap


def patch_labels(snap: Snap, row: int, lab: int) -> None:
    snap.labels[row] = lab  # in-place write on an annotated snapshot


class Serve:
    def __init__(self) -> None:
        self._snap = Snap(0, np.zeros(4, np.int64))

    def absorb_in_place(self, row: int, lab: int) -> None:
        snap = self._snap
        snap.labels[row] = lab               # element write via alias
        snap.labels.sort()                   # mutating method call
        np.minimum.at(snap.labels, row, lab)  # numpy in-place sink
        patch_labels(self._snap, row, lab)   # mutation one call away
