"""BAD: a per-range append reached without any lease fence."""

from .coordinator import verify_lease


class SignatureStore:
    def __init__(self, root):
        self.root = root

    def append(self, rows):
        return len(rows)


class ShardedSignatureStore:
    def __init__(self, root):
        self.root = root

    def range_store(self, r):
        store = SignatureStore(self.root)
        return store

    def append_unfenced(self, rows):
        # BAD: nothing dominates this per-range append — a superseded
        # writer would double-write its re-dealt range.
        return self.range_store(0).append(rows)

    def append_fenced(self, rows):
        verify_lease(self.root, 0)
        return self.range_store(0).append(rows)
