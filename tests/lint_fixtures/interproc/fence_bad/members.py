"""BAD: membership.json mutated directly instead of through the
MembershipLedger (a torn or non-monotonic write breaks epoch fencing)."""

import json
import os


def rewrite_membership(pod_dir, members):
    with open(os.path.join(pod_dir, "membership.json"), "w") as f:
        json.dump({"members": members}, f)
    os.replace("unused", "unused2")  # keep the per-file rule quiet
