"""BAD: a supervision path whose broad except absorbs the fence signal
(LeaseSupersededError raised three calls down)."""

from .store import ShardedSignatureStore


def supervise(rows):
    st = ShardedSignatureStore("/tmp/x")
    try:
        return st.append_fenced(rows)
    except Exception:
        return None  # BAD: the zombie fence signal dies here
