"""Fixture stand-in for resilience/coordinator.py's lease plane."""


class LeaseSupersededError(RuntimeError):
    pass


def verify_lease(root, range_id):
    raise LeaseSupersededError(range_id)
