"""Fixture spec verb alphabets — all four surfaces agree exactly."""

SERVER_VERBS = ("ping", "query")
ROUTER_VERBS = ("ping",)
CLIENT_VERBS = ("ping", "query")
FORWARD_VERBS = ("ping",)
