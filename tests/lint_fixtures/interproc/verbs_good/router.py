"""GOOD: router dispatch and in-process forwarding agree with their
alphabets."""


class RouterServer:
    def _dispatch_op(self, op, msg):
        if op == "ping":
            return {"ok": True}
        return {"ok": False}


class LocalTransport:
    def __call__(self, msg):
        op = str(msg.get("op", ""))
        if op == "ping":
            return {"ok": True}
        return {"ok": False}
