"""GOOD: the server dispatch table matches SERVER_VERBS exactly."""


class ServeServer:
    def _dispatch_op(self, op, msg):
        if op == "ping":
            return {"ok": True}
        if op == "query":
            return {"ok": True, "labels": []}
        return {"ok": False}
