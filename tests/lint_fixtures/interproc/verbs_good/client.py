"""GOOD: the client's request-issuing methods cover CLIENT_VERBS
exactly (the transport helper issues no verb literal itself)."""


class ServeClient:
    def request(self, op, **kw):
        return {"op": op, **kw}

    def ping(self):
        return self.request("ping")

    def query(self, vectors):
        return self.request("query", vectors=vectors)
