"""BAD: dead spec actions of every class — a fault seat no
fault_point declares, a verb no surface dispatches, a call target
that does not exist, an unknown seat kind, and a seat that is not a
string literal."""

SPEC_NAME = "toy"

SEAT = "fault:io.write"


class Action:  # stand-in for tse1m_tpu.spec.dsl.Action
    def __init__(self, name, guard, effect, seat="model:env",
                 fair=False):
        pass


def build():
    return (
        Action("dead_fault", lambda s: True, lambda s: s,
               seat="fault:io.missing"),
        Action("dead_verb", lambda s: True, lambda s: s,
               seat="verb:evict"),
        Action("dead_call", lambda s: True, lambda s: s,
               seat="call:no_such_fn"),
        Action("bad_kind", lambda s: True, lambda s: s,
               seat="oops:x"),
        Action("dyn_seat", lambda s: True, lambda s: s, seat=SEAT),
    )
