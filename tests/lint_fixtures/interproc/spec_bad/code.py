"""BAD: the module declares spec bindings that do not hold — one
named spec does not exist, and a fault seat here is absent from every
spec it binds to."""

SPEC_MODELS = ("toy", "ghost")


def fault_point(site, path=None):  # stand-in for resilience.faults
    pass


def save(path):
    fault_point("io.unmodeled", path=path)
