"""GOOD twin: beta's flush releases its lock before calling back into
alpha (no hold-and-acquire in the reverse order), and the re-entrant
path uses an RLock."""

import threading

from . import alpha


class Monitor:
    def __init__(self):
        self._lock = threading.Lock()
        self._rent = threading.RLock()

    def poll(self):
        with self._lock:
            pass

    def flush(self):
        with self._lock:
            pending = True
        if pending:
            r = alpha.Recorder()
            r.add()

    def reenter(self):
        with self._rent:
            with self._rent:
                pass
