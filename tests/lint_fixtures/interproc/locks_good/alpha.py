"""GOOD twin: both modules take the locks in one global order
(alpha._lock before beta._lock, never the reverse)."""

import threading

from . import beta


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()

    def add(self):
        with self._lock:
            m = beta.Monitor()
            m.poll()
