"""Blessed interpolations: ident helpers, int(), placeholder lists."""
from tse1m_tpu.db.ident import col_list, quote_ident


def count_rows(db, table):
    return db.query(f"SELECT COUNT(*) FROM {quote_ident(table)}")


def insert(table, cols):
    ph = ", ".join("?" * len(cols))
    return f"INSERT INTO {quote_ident(table)} ({col_list(cols)}) VALUES ({ph})"


def timeout(ms):
    return f"SET statement_timeout = {int(ms)}"


def no_sql(name):
    return f"hello {name}"
