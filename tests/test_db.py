"""DB layer: schema, ingest, parameterized queries, columnar extraction."""

import numpy as np

from tse1m_tpu.config import Config
from tse1m_tpu.data.columnar import StudyArrays, ns_to_device_s
from tse1m_tpu.db import queries
from tse1m_tpu.db.connection import DB
from tse1m_tpu.db.ingest import canon_result, parse_array, pg_array_literal, ingest_csv_dir


def test_parse_array_forms():
    assert parse_array("{a,b}") == ["a", "b"]
    assert parse_array('["a","b"]') == ["a", "b"]
    assert parse_array("") == []
    assert parse_array(None) == []
    assert parse_array("{}") == []
    assert pg_array_literal(["x", "y"]) == "{x,y}"


def test_canon_result():
    assert canon_result("Success") == "Finish"
    assert canon_result("Finish") == "Finish"
    assert canon_result("Halfway") == "Halfway"
    assert canon_result(None) == "Unknown"


def test_synth_to_db_roundtrip(study_db, synth_study):
    (n_builds,) = study_db.query("SELECT COUNT(*) FROM buildlog_data")[0]
    assert n_builds == len(synth_study.buildlog_data)
    (n_issues,) = study_db.query("SELECT COUNT(*) FROM issues")[0]
    assert n_issues == len(synth_study.issues)


def test_eligible_projects_threshold(study_db, synth_study):
    sql, params = queries.eligible_projects(365, "2026-01-01")
    eligible = {r[0] for r in study_db.query(sql, params)}
    cov = synth_study.total_coverage
    expected = {
        p for p, grp in cov.groupby("project")
        if (grp["coverage"] > 0).sum() >= 365
    }
    assert eligible == expected
    assert 0 < len(eligible) < synth_study.project_info.shape[0] + 1


def test_same_date_build_issue_window_join(study_db):
    sql, params = queries.eligible_projects(365, "2026-01-01")
    targets = [r[0] for r in study_db.query(sql, params)]
    sql, params = queries.same_date_build_issue(targets, "2026-01-01")
    rows = study_db.query(sql, params)
    assert rows, "window-function join returned no linked issues"
    # rn=1 guarantees one row per (project, number).
    keys = [(r[1], r[0]) for r in rows]
    assert len(keys) == len(set(keys))
    # Linked build strictly precedes the issue report time.
    for r in rows[:200]:
        assert r[3] < r[2]


def test_columnar_extraction(study_db, study_cfg, synth_study):
    arrays = StudyArrays.from_db(study_db, study_cfg)
    assert arrays.n_projects > 0
    # Segments are time-sorted.
    for p in range(arrays.n_projects):
        t = arrays.fuzz.segment(p)["time_ns"]
        assert np.all(np.diff(t) >= 0)
    # Totals line up with the DB.
    (total_fuzz,) = study_db.query(
        "SELECT COUNT(*) FROM buildlog_data WHERE build_type='Fuzzing' AND project IN ("
        + ",".join("?" * arrays.n_projects) + ")",
        arrays.projects,
    )[0]
    assert len(arrays.fuzz) == total_fuzz
    # Device views are int32 seconds and order-preserving.
    dev = arrays.device_times()
    assert dev["fuzz_times_s"].dtype == np.int32
    assert np.all(np.diff(dev["fuzz_times_s"][: dev["fuzz_offsets"][1]]) >= 0)


def test_ingest_csv_dir(tmp_path, synth_study):
    csv_dir = tmp_path / "csv"
    synth_study.to_csv_dir(str(csv_dir))
    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / "ing.sqlite"))
    db = DB(config=cfg).connect()
    counts = ingest_csv_dir(db, str(csv_dir))
    assert counts["buildlog_data"] == len(synth_study.buildlog_data)
    assert counts["issues"] == len(synth_study.issues)
    assert counts["total_coverage"] == len(synth_study.total_coverage)
    db.closeConnection()


def test_device_seconds_strictness():
    # issue > build comparisons survive the ns->s quantisation in fixtures.
    ns = np.array([1_700_000_000_000_000_000, 1_700_000_003_000_000_000])
    s = ns_to_device_s(ns)
    assert s[1] > s[0]


LIMIT = "2026-01-01"


def _eligible(study_db):
    sql, params = queries.eligible_projects(365, LIMIT)
    return sorted(r[0] for r in study_db.query(sql, params))


def test_per_project_fuzzing_builders_match_bulk(study_db):
    """The per-project reference-parity builders (ALL_FUZZING_BUILD
    queries1.py:267, SUCCESSED_FUZZING_BUILD queries1.py:61) must agree
    with the bulk variants the engine actually uses."""
    from tse1m_tpu.config import RESULT_OK

    targets = _eligible(study_db)
    sql, params = queries.all_fuzzing_builds_bulk(targets)
    bulk = study_db.query(sql, params)
    checked = 0
    for project in targets[:4]:
        sql, params = queries.all_fuzzing_build(project)
        per = study_db.query(sql, params)
        assert per == [(r[1], r[2]) for r in bulk if r[0] == project]
        sql, params = queries.successful_fuzzing_build(project)
        per_ok = study_db.query(sql, params)
        assert per_ok == [(r[1], r[2]) for r in bulk
                          if r[0] == project and r[3] in RESULT_OK]
        checked += len(per)
    assert checked > 0


def test_per_project_coverage_builders_match_bulk(study_db):
    """GET_COVERAGE_BUILDS (queries1.py:94, the live non-shadowed variant:
    result='Finish' only) and GET_TOTAL_COVERAGE_EACH_PROJECT
    (queries1.py:120) vs the unfiltered bulk fetches."""
    targets = _eligible(study_db)
    sql, params = queries.coverage_builds_bulk(targets)
    bulk = study_db.query(sql, params)
    sql, params = queries.total_coverage_bulk(targets, LIMIT)
    cov_bulk = study_db.query(sql, params)
    for project in targets[:4]:
        sql, params = queries.coverage_builds(project)
        per = study_db.query(sql, params)
        # bulk rows: (project, timecreated, modules, revisions, result) —
        # no name (nothing consumes coverage-build names); compare the
        # per-project builder's rows projected onto the bulk columns.
        per_proj = [(r[1], r[2], r[5], r[6], r[4]) for r in per]
        expect = [r for r in bulk if r[0] == project and r[4] == "Finish"]
        assert per_proj == expect
        sql, params = queries.total_coverage_each_project(
            project, "coverage", LIMIT)
        per_cov = study_db.query(sql, params)
        expect_cov = [(r[3], r[4]) for r in cov_bulk
                      if r[0] == project and r[2] not in (None, 0)]
        assert per_cov == expect_cov


def test_total_coverage_each_project_whitelists_columns(study_db):
    import pytest

    with pytest.raises(ValueError):
        queries.total_coverage_each_project("p", "coverage; DROP TABLE x")


def test_count_projects_frequency(study_db):
    sql, params = queries.count_projects()
    freq = dict(study_db.query(sql, params))
    oracle = dict(study_db.query(
        "SELECT project, COUNT(*) FROM buildlog_data GROUP BY project"))
    assert freq == oracle and freq


def test_severity_issues_oracle(study_db, synth_study):
    """severity_issues (queries1.py:104-118) vs a pandas re-derivation:
    issues of that severity with a non-empty regressed_build array."""
    targets = _eligible(study_db)
    df = synth_study.issues
    df = df[df["project"].isin(targets)]
    found_any = 0
    for severity in ("High", "Medium", "Low"):
        sql, params = queries.severity_issues(
            severity, targets, study_db.dialect, LIMIT)
        rows = study_db.query(sql, params)
        sub = df[(df["severity"] == severity)
                 & (df["rts"] < LIMIT)
                 & df["regressed_build"].map(
                     lambda v: len(parse_array(v)) > 0)]
        assert len(rows) == len(sub), severity
        assert all(r[3] == severity for r in rows)
        found_any += len(rows)
    assert found_any > 0


def test_issues_without_matching_build_oracle(study_db, synth_study):
    """GET_ISSUES_WITHOUT_MATCHING_BUILD (queries1.py:280-314; consumed by
    run_rq1's diagnostic, reference rq1:161-163) vs a pandas re-derivation
    of the NOT EXISTS predicate."""
    import pandas as pd

    from tse1m_tpu.config import FIXED_STATUSES, RESULT_OK

    targets = _eligible(study_db)
    sql, params = queries.issues_without_matching_build(targets, LIMIT)
    rows = study_db.query(sql, params)

    builds = synth_study.buildlog_data
    builds = builds[(builds["build_type"] == "Fuzzing")
                    & builds["result"].isin(RESULT_OK)
                    & (builds["timecreated"] < LIMIT)]
    by_proj = {p: sorted(g["timecreated"]) for p, g in
               builds.groupby("project")}
    issues = synth_study.issues
    issues = issues[issues["project"].isin(targets)
                    & issues["status"].isin(FIXED_STATUSES)]
    expect = set()
    for _, row in issues.iterrows():
        blds = by_proj.get(row["project"], [])
        if not any(bt < row["rts"] for bt in blds):
            expect.add((row["project"], str(row["number"])))
    assert {(r[0], str(r[1])) for r in rows} == expect


def test_cli_stats_smoke(study_db, capsys):
    from tse1m_tpu.cli import main

    rc = main(["stats", "--db", study_db.config.sqlite_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "buildlog_data" in out and "severity High" in out
    assert "eligible" in out
