"""DB layer: schema, ingest, parameterized queries, columnar extraction."""

import numpy as np

from tse1m_tpu.config import Config
from tse1m_tpu.data.columnar import StudyArrays, ns_to_device_s
from tse1m_tpu.db import queries
from tse1m_tpu.db.connection import DB
from tse1m_tpu.db.ingest import canon_result, parse_array, pg_array_literal, ingest_csv_dir


def test_parse_array_forms():
    assert parse_array("{a,b}") == ["a", "b"]
    assert parse_array('["a","b"]') == ["a", "b"]
    assert parse_array("") == []
    assert parse_array(None) == []
    assert parse_array("{}") == []
    assert pg_array_literal(["x", "y"]) == "{x,y}"


def test_canon_result():
    assert canon_result("Success") == "Finish"
    assert canon_result("Finish") == "Finish"
    assert canon_result("Halfway") == "Halfway"
    assert canon_result(None) == "Unknown"


def test_synth_to_db_roundtrip(study_db, synth_study):
    (n_builds,) = study_db.query("SELECT COUNT(*) FROM buildlog_data")[0]
    assert n_builds == len(synth_study.buildlog_data)
    (n_issues,) = study_db.query("SELECT COUNT(*) FROM issues")[0]
    assert n_issues == len(synth_study.issues)


def test_eligible_projects_threshold(study_db, synth_study):
    sql, params = queries.eligible_projects(365, "2026-01-01")
    eligible = {r[0] for r in study_db.query(sql, params)}
    cov = synth_study.total_coverage
    expected = {
        p for p, grp in cov.groupby("project")
        if (grp["coverage"] > 0).sum() >= 365
    }
    assert eligible == expected
    assert 0 < len(eligible) < synth_study.project_info.shape[0] + 1


def test_same_date_build_issue_window_join(study_db):
    sql, params = queries.eligible_projects(365, "2026-01-01")
    targets = [r[0] for r in study_db.query(sql, params)]
    sql, params = queries.same_date_build_issue(targets, "2026-01-01")
    rows = study_db.query(sql, params)
    assert rows, "window-function join returned no linked issues"
    # rn=1 guarantees one row per (project, number).
    keys = [(r[1], r[0]) for r in rows]
    assert len(keys) == len(set(keys))
    # Linked build strictly precedes the issue report time.
    for r in rows[:200]:
        assert r[3] < r[2]


def test_columnar_extraction(study_db, study_cfg, synth_study):
    arrays = StudyArrays.from_db(study_db, study_cfg)
    assert arrays.n_projects > 0
    # Segments are time-sorted.
    for p in range(arrays.n_projects):
        t = arrays.fuzz.segment(p)["time_ns"]
        assert np.all(np.diff(t) >= 0)
    # Totals line up with the DB.
    (total_fuzz,) = study_db.query(
        "SELECT COUNT(*) FROM buildlog_data WHERE build_type='Fuzzing' AND project IN ("
        + ",".join("?" * arrays.n_projects) + ")",
        arrays.projects,
    )[0]
    assert len(arrays.fuzz) == total_fuzz
    # Device views are int32 seconds and order-preserving.
    dev = arrays.device_times()
    assert dev["fuzz_times_s"].dtype == np.int32
    assert np.all(np.diff(dev["fuzz_times_s"][: dev["fuzz_offsets"][1]]) >= 0)


def test_ingest_csv_dir(tmp_path, synth_study):
    csv_dir = tmp_path / "csv"
    synth_study.to_csv_dir(str(csv_dir))
    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / "ing.sqlite"))
    db = DB(config=cfg).connect()
    counts = ingest_csv_dir(db, str(csv_dir))
    assert counts["buildlog_data"] == len(synth_study.buildlog_data)
    assert counts["issues"] == len(synth_study.issues)
    assert counts["total_coverage"] == len(synth_study.total_coverage)
    db.closeConnection()


def test_device_seconds_strictness():
    # issue > build comparisons survive the ns->s quantisation in fixtures.
    ns = np.array([1_700_000_000_000_000_000, 1_700_000_003_000_000_000])
    s = ns_to_device_s(ns)
    assert s[1] > s[0]
