"""Golden-format regression vs the reference's committed artifacts.

The reference ships its full-run outputs under ``data/result_data/**``
(SURVEY.md §4(2)) — those CSVs are the format contract a drop-in rebuild
must honor.  Each test runs the repo's writer on the synthetic study and
asserts the emitted header, column order, and value formats are identical
to the same-named reference artifact, so any writer drift fails CI.

Goldens covered (everything CSV the snapshot retains — four files are
stripped, ``/root/reference/.MISSING_LARGE_BLOBS:1-5``):

- rq1/rq1_detection_rate_stats.csv        (int,int,int rows)
- rq3/change_analysis/<project>.csv       (per-project change schema)
- rq3/detected_coverage_changes.csv       (float,int,int rows)
- rq4/bug/rq4_g1_g2_detection_trend.csv   (iteration + per-group rates)
- rq4/bug/rq4_gc_introduction_iteration.csv
"""

import csv
import os
import re

import pytest

from tse1m_tpu.analysis.rq1 import run_rq1
from tse1m_tpu.analysis.rq2_changepoints import run_rq2_changepoints
from tse1m_tpu.analysis.rq3 import run_rq3
from tse1m_tpu.analysis.rq4a import run_rq4a
from tse1m_tpu.config import Config

REF = "/root/reference/data/result_data"
LIMIT = "2026-01-01"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference snapshot not available")

TS_RE = re.compile(r"^\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2}(\.\d+)?$")
PG_ARRAY_RE = re.compile(r"^\{[^{}]*\}$")
INT_RE = re.compile(r"^-?\d+$")
FLOAT_RE = re.compile(r"^-?\d+(\.\d+)?([eE]-?\d+)?$")


def read_csv(path):
    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    return rows[0], rows[1:]


@pytest.fixture(scope="module")
def artifacts(study_db, synth_study, tmp_path_factory):
    """Run every writer once against the synth study."""
    out = tmp_path_factory.mktemp("golden")
    corpus = out / "project_corpus_analysis.csv"
    synth_study.corpus_analysis.to_csv(corpus, index=False)
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT, backend="jax_tpu",
                 result_dir=str(out), corpus_csv=str(corpus))
    cfg.min_projects_per_iteration = 2
    run_rq1(cfg, db=study_db)
    run_rq2_changepoints(cfg, db=study_db)
    run_rq3(cfg, db=study_db)
    run_rq4a(cfg, db=study_db)
    return str(out)


def assert_row_formats(rows, patterns, label):
    assert rows, f"{label}: writer emitted no data rows"
    for row in rows[:50]:
        assert len(row) == len(patterns), f"{label}: width {len(row)}"
        for val, pat in zip(row, patterns):
            if pat is not None:
                assert pat.match(val), f"{label}: {val!r} !~ {pat.pattern}"


def formats_of(path, patterns, limit=50):
    """Assert the reference's own rows match `patterns` too — guards the
    test itself against drifting from the artifact it encodes."""
    _, rows = read_csv(path)
    for row in rows[:limit]:
        for val, pat in zip(row, patterns):
            if pat is not None:
                assert pat.match(val), f"reference {path}: {val!r}"


def test_rq1_stats_format(artifacts):
    ref_header, ref_rows = read_csv(f"{REF}/rq1/rq1_detection_rate_stats.csv")
    got_header, got_rows = read_csv(
        os.path.join(artifacts, "rq1", "rq1_detection_rate_stats.csv"))
    assert got_header == ref_header == [
        "Iteration", "Total_Projects", "Detected_Projects_Count"]
    assert ref_rows[0] == ["1", "878", "297"]  # SURVEY §4(2) anchor
    pats = [INT_RE, INT_RE, INT_RE]
    formats_of(f"{REF}/rq1/rq1_detection_rate_stats.csv", pats)
    assert_row_formats(got_rows, pats, "rq1 stats")
    # Iterations ascend from 1 in both.
    assert [r[0] for r in got_rows[:3]] == ["1", "2", "3"]


def test_rq3_change_analysis_per_project_format(artifacts):
    ref_path = f"{REF}/rq3/change_analysis/abseil-cpp.csv"
    ref_header, _ = read_csv(ref_path)
    change_dir = os.path.join(artifacts, "rq3", "change_analysis")
    ours = sorted(os.listdir(change_dir))
    assert ours, "no per-project change CSVs emitted"
    got_header, got_rows = read_csv(os.path.join(change_dir, ours[0]))
    assert got_header == ref_header
    # project, ts, {mods}, {revs}, ts, {mods}, {revs}, 4x float, int-or-float
    pats = [None, TS_RE, PG_ARRAY_RE, PG_ARRAY_RE, TS_RE, PG_ARRAY_RE,
            PG_ARRAY_RE, FLOAT_RE, FLOAT_RE, FLOAT_RE, FLOAT_RE,
            FLOAT_RE, FLOAT_RE]
    formats_of(ref_path, pats)
    assert_row_formats(got_rows, pats, "rq3 change_analysis")


def test_rq3_merged_change_analysis_format(artifacts):
    ref_header, _ = read_csv(f"{REF}/rq3/change_analysis/abseil-cpp.csv")
    got_header, got_rows = read_csv(
        os.path.join(artifacts, "rq3", "all_coverage_change_analysis.csv"))
    # The merged file shares the per-project schema (rq2:222-238).
    assert got_header == ref_header
    assert got_rows


def test_rq3_detected_changes_format(artifacts):
    ref_path = f"{REF}/rq3/detected_coverage_changes.csv"
    ref_header, _ = read_csv(ref_path)
    got_header, got_rows = read_csv(
        os.path.join(artifacts, "rq3", "detected_coverage_changes.csv"))
    assert got_header == ref_header == [
        "CoverageChangePercent", "CoveredLinesChange", "TotalLinesChange"]
    pats = [FLOAT_RE, INT_RE, INT_RE]
    formats_of(ref_path, pats)
    assert_row_formats(got_rows, pats, "rq3 detected")


def test_rq4a_trend_format(artifacts):
    ref_path = f"{REF}/rq4/bug/rq4_g1_g2_detection_trend.csv"
    ref_header, ref_rows = read_csv(ref_path)
    got_header, got_rows = read_csv(
        os.path.join(artifacts, "rq4", "bug",
                     "rq4_g1_g2_detection_trend.csv"))
    assert got_header == ref_header == [
        "Iteration", "G1_Total_Projects", "G1_Detected_Count",
        "G1_Detection_Rate_pct", "G2_Total_Projects", "G2_Detected_Count",
        "G2_Detection_Rate_pct"]
    pats = [INT_RE, INT_RE, INT_RE, FLOAT_RE, INT_RE, INT_RE, FLOAT_RE]
    formats_of(ref_path, pats)
    assert_row_formats(got_rows, pats, "rq4a trend")
    # Rates are full-precision repr floats in the reference (e.g.
    # 33.33333333333333) — ours must not round/format-truncate.
    assert any("." in r[3] and len(r[3].split(".")[1]) > 6
               for r in ref_rows[:5])


def test_rq4a_introduction_iteration_format(artifacts):
    ref_path = f"{REF}/rq4/bug/rq4_gc_introduction_iteration.csv"
    ref_header, _ = read_csv(ref_path)
    got_header, got_rows = read_csv(
        os.path.join(artifacts, "rq4", "bug",
                     "rq4_gc_introduction_iteration.csv"))
    assert got_header == ref_header == ["Project", "Introduction_Iteration"]
    pats = [None, INT_RE]
    formats_of(ref_path, pats)
    assert_row_formats(got_rows, pats, "rq4a introduction")
