"""RQ3: backend parity (pandas vs jax), oracle correctness vs a brute-force
re-derivation of the reference's per-issue loop
(rq3_diff_coverage_at_detection.py:241-302), and end-to-end artifacts."""

import os

import numpy as np
import pandas as pd
import pytest

from tse1m_tpu.analysis.rq3 import run_rq3, summary_statistics
from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.config import Config, RESULT_OK
from tse1m_tpu.data.columnar import StudyArrays

LIMIT = "2026-01-01"


@pytest.fixture(scope="module")
def arrays(study_db):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT)
    return StudyArrays.from_db(study_db, cfg)


@pytest.fixture(scope="module")
def limit_ns():
    return int(np.datetime64(LIMIT, "ns").astype(np.int64))


@pytest.fixture(scope="module")
def pd_result(arrays, limit_ns):
    return PandasBackend().rq3_coverage_at_detection(arrays, limit_ns)


def test_backend_parity(arrays, limit_ns, pd_result):
    jx = JaxBackend().rq3_coverage_at_detection(arrays, limit_ns)
    for f in ("det_diff_percent", "det_diff_covered", "det_diff_total",
              "det_project_idx", "det_issue_idx", "det_issue_time_ns",
              "nondet_diff_percent", "nondet_diff_covered",
              "nondet_diff_total", "nondet_project_idx"):
        np.testing.assert_array_equal(getattr(pd_result, f), getattr(jx, f),
                                      err_msg=f)


def test_fixture_has_signal(pd_result):
    # The synthetic study must exercise both branches non-trivially.
    assert pd_result.det_diff_percent.size >= 10
    assert pd_result.nondet_diff_percent.size >= 1000


def test_oracle_reference_semantics(arrays, limit_ns, study_db, pd_result):
    """Replay the reference's Python control flow straight from DB rows."""
    day = np.timedelta64(1, "D")
    limit = np.datetime64(LIMIT)
    limit_p1 = str(limit + day)

    detected, non_detected = [], []
    for proj in arrays.projects:
        issues = study_db.query(
            "SELECT rts FROM issues WHERE project=? AND rts<? AND status IN "
            "('Fixed','Fixed (Verified)') ORDER BY rts, number", (proj, LIMIT))
        if not issues:
            continue
        fuzz = study_db.query(
            "SELECT timecreated, revisions FROM buildlog_data WHERE project=? "
            f"AND build_type='Fuzzing' AND result IN {tuple(RESULT_OK)} "
            "AND timecreated<? ORDER BY timecreated", (proj, LIMIT))
        covb = study_db.query(
            "SELECT timecreated, revisions, result FROM buildlog_data "
            "WHERE project=? AND build_type='Coverage' AND timecreated<? "
            "ORDER BY timecreated", (proj, limit_p1))
        cov = study_db.query(
            "SELECT date, covered_line, total_line FROM total_coverage "
            "WHERE project=? AND covered_line IS NOT NULL AND date<? "
            "ORDER BY date", (proj, limit_p1))
        det_days = set()
        for (rts,) in issues if (fuzz and covb and cov) else []:
            rts_ts = pd.Timestamp(rts)
            lf = next((b for b in reversed(fuzz)
                       if pd.Timestamp(b[0]) < rts_ts), None)
            if lf is None:
                continue
            fc = next((b for b in covb if pd.Timestamp(b[0]) > rts_ts), None)
            if fc is None or fc[2] not in RESULT_OK:
                continue
            gap = (pd.Timestamp(fc[0]) - pd.Timestamp(lf[0])).total_seconds()
            if gap / 3600 > 24:
                continue
            strip = lambda s: sorted(s.strip("{}").split(","))  # noqa: E731
            if strip(lf[1]) != strip(fc[1]):
                continue
            target = rts_ts.normalize() + pd.Timedelta(days=1)
            pair = None
            for i in range(1, len(cov)):
                if pd.Timestamp(cov[i][0]) == target:
                    if cov[i][1] == 0:
                        break
                    pair = (cov[i - 1], cov[i])
                    break
            if pair and pair[0][2] > 0 and pair[1][2] > 0:
                detected.append(
                    (pair[1][1] / pair[1][2] - pair[0][1] / pair[0][2]) * 100)
                det_days.add(rts_ts.normalize())
        for i in range(1, len(cov)):
            if pd.Timestamp(cov[i][0]) in det_days:
                continue
            if cov[i - 1][2] > 0 and cov[i][2] > 0:
                non_detected.append(
                    (cov[i][1] / cov[i][2] - cov[i - 1][1] / cov[i - 1][2]) * 100)

    np.testing.assert_allclose(np.sort(pd_result.det_diff_percent),
                               np.sort(detected), rtol=1e-12)
    np.testing.assert_allclose(np.sort(pd_result.nondet_diff_percent),
                               np.sort(non_detected), rtol=1e-12)


def test_summary_statistics():
    s = summary_statistics(np.array([-1.0, 0.0, 1.0, 3.0]))
    assert s["count"] == 4
    assert s["positive_pct"] == 50.0
    assert s["zero_pct"] == 25.0
    assert s["negative_pct"] == 25.0
    assert s["median"] == 0.5


@pytest.mark.parametrize("backend", ["pandas", "jax_tpu", "auto"])
def test_run_rq3_end_to_end(study_db, tmp_path, backend):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 backend=backend, result_dir=str(tmp_path), limit_date=LIMIT)
    out = run_rq3(cfg, db=study_db)
    assert os.path.exists(out["detected_csv"])
    df = pd.read_csv(out["detected_csv"])
    assert list(df.columns) == ["CoverageChangePercent", "CoveredLinesChange",
                                "TotalLinesChange"]
    assert len(df) == out["summary"]["detected"]["count"]
    assert "brunner_munzel" in out["tests"]
    for pdf in ("coverage_diff_boxplot.pdf", "coverage_diff_histograms.pdf",
                "detected.pdf", "non_detected.pdf"):
        assert os.path.exists(tmp_path / "rq3" / pdf)
