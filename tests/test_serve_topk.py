"""The ``topk`` serve verb end to end (daemon, TCP server/client,
2-shard router fan-out, read replica).

The load-bearing claims:

- scan mode is EXACT: its top-k equals the host oracle over the
  daemon's store rows (recall 1.0), and the candidate path's hits are
  always a subset scored identically;
- the router's merged scan answer is elementwise-equal (ids AND
  scores) to a single unsharded daemon over the same rows, given
  planted strict score separation at the k boundary (agreement-count
  ties at the boundary are row-order dependent per shard — the
  documented caveat);
- a replica answers ``topk`` read-only over its streamed copy;
- the wire contract holds over real TCP (hex digest ids, -1/"" pads,
  np-typed scores/labels client-side), and ``status`` splits latency
  per verb instead of one blended histogram.
"""

from __future__ import annotations

import numpy as np
import pytest

from tse1m_tpu.cluster import ClusterParams
from tse1m_tpu.cluster.kernels.score import score_topk_host
from tse1m_tpu.serve import (LocalTransport, ServeClient, ServeDaemon,
                             ServeError, ServeReplica, ServeServer,
                             ShardRouter, stream_shards)

PARAMS = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")


def _planted(n_family: int = 12, n_filler: int = 40, seed: int = 5,
             width: int = 16):
    """(vectors, queries): a corruption ladder around one base vector —
    row i of the family disagrees with the base on exactly i positions,
    so agreement counts are strictly separated (router merge parity
    needs no boundary ties) — plus content-distinct filler."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 2**32, size=(1, width), dtype=np.int64
                        ).astype(np.uint32)
    fam = np.repeat(base, n_family, axis=0)
    for i in range(n_family):
        fam[i, :i] = rng.integers(1, 2**32, size=i,
                                  dtype=np.int64).astype(np.uint32)
    filler = rng.integers(0, 2**32, size=(n_filler, width),
                          dtype=np.int64).astype(np.uint32)
    return np.concatenate([fam, filler]), base


def _store_sigs(daemon: ServeDaemon) -> np.ndarray:
    """Every committed signature row in scan order (sorted shard id)."""
    store = daemon.reader
    store.refresh()
    return np.concatenate(
        [np.asarray(store._sig_mmap(int(e["id"])))
         for e in sorted(store.shards, key=lambda e: int(e["id"]))])


# -- daemon verb -------------------------------------------------------------

def test_daemon_scan_matches_host_oracle(tmp_path):
    vecs, q = _planted()
    d = ServeDaemon(str(tmp_path / "s"), params=PARAMS,
                    state_commit_every=1, signer="host").start()
    try:
        d.ingest(vecs, timeout=60)
        res = d.topk(q, k=5, mode="scan")
        # exact-recall contract: scores equal the host oracle's over
        # every committed store row
        ref_counts, _ = score_topk_host(
            d._sign_novel(q), _store_sigs(d), 5)
        assert res["scores"] == ref_counts.tolist()
        assert res["scores"][0][0] == PARAMS.n_hashes  # self-hit
        assert all(len(r) == 5 for r in res["ids"])
        assert all(len(i) == 32 for i in res["ids"][0])  # hex digests
        # candidate mode scores any hit it finds identically (a subset
        # of the scan's universe — here the self-hit at full agreement)
        cand = d.topk(q, k=5, mode="candidates")
        assert cand["scores"][0][0] == PARAMS.n_hashes
        assert cand["ids"][0][0] == res["ids"][0][0]
        with pytest.raises(ValueError):
            d.topk(q, k=3, mode="nope")
    finally:
        d.stop(commit=False)


def test_daemon_topk_edges(tmp_path):
    vecs, q = _planted(n_family=3, n_filler=5)
    d = ServeDaemon(str(tmp_path / "s"), params=PARAMS,
                    state_commit_every=1, signer="host").start()
    try:
        d.ingest(vecs, timeout=60)
        empty = d.topk(np.zeros((0, 16), np.uint32), k=4, mode="scan")
        assert empty["scores"] == [] and empty["ids"] == []
        k0 = d.topk(q, k=0, mode="scan")
        assert k0["scores"] == [[]]
        # k past the row count pads with ("", -1, -1)
        big = d.topk(q, k=20, mode="scan")
        n = vecs.shape[0]
        assert big["scores"][0][n:] == [-1] * (20 - n)
        assert big["ids"][0][n:] == [""] * (20 - n)
        assert big["labels"][0][n:] == [-1] * (20 - n)
    finally:
        d.stop(commit=False)


# -- router fan-out parity ---------------------------------------------------

def test_router_topk_parity_vs_single_daemon(tmp_path):
    # Two independent corruption ladders: both probes see strictly
    # separated top-6 scores (ties only start past each family's size).
    fam_a, base_a = _planted(seed=5)
    fam_b, base_b = _planted(seed=6)
    vecs = np.concatenate([fam_a, fam_b])
    q = np.concatenate([base_a, base_b])
    single = ServeDaemon(str(tmp_path / "single"), params=PARAMS,
                         state_commit_every=1, signer="host").start()
    shards = {sid: ServeDaemon(str(tmp_path / f"range_{sid:04d}"),
                               params=PARAMS, state_commit_every=1,
                               signer="host").start() for sid in (0, 1)}
    try:
        single.ingest(vecs, timeout=60)
        router = ShardRouter({s: LocalTransport(d)
                              for s, d in shards.items()})
        router.ingest(vecs, timeout=60)
        ref = single.topk(q, k=6, mode="scan")
        got = router.topk(q, k=6, mode="scan")
        # elementwise: same digests in the same order with same scores
        assert got["ids"] == ref["ids"]
        assert got["scores"] == ref["scores"]
        assert set(got["shard_generations"]) == {0, 1}
        # candidate mode fans out the same way (self-hit from the
        # owning shard ranks first at full agreement)
        cand = router.topk(base_a, k=3, mode="candidates")
        assert cand["scores"][0][0] == PARAMS.n_hashes
        assert cand["ids"][0][0] == ref["ids"][0][0]
    finally:
        single.stop(commit=False)
        for d in shards.values():
            d.stop(commit=False)


# -- replica -----------------------------------------------------------------

def test_replica_answers_topk_read_only(tmp_path):
    vecs, q = _planted()
    src = str(tmp_path / "writer")
    dst = str(tmp_path / "replica")
    d = ServeDaemon(src, params=PARAMS, state_commit_every=1,
                    signer="host").start()
    try:
        d.ingest(vecs, timeout=60)
        d.quiesce(timeout=60)
        ref = d.topk(q, k=4, mode="scan")
    finally:
        d.stop()
    stream_shards(src, dst)
    rep = ServeReplica(dst, params=PARAMS)
    for mode in ("scan", "candidates"):
        res = rep.topk(q, k=4, mode=mode)
        assert res["scores"][0][0] == PARAMS.n_hashes
    assert rep.topk(q, k=4, mode="scan")["ids"] == ref["ids"]
    st = rep.status()
    assert st["read_only"] is True
    assert st["latency_by_verb"]["topk"]["count"] >= 3
    with pytest.raises(RuntimeError):
        rep.ingest(q)


# -- TCP wire contract + per-verb latency ------------------------------------

def test_topk_over_tcp_and_per_verb_status(tmp_path):
    import threading

    vecs, q = _planted()
    d = ServeDaemon(str(tmp_path / "s"), params=PARAMS,
                    state_commit_every=1, signer="host").start()
    server = ServeServer(d)
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.05}, daemon=True)
    t.start()
    try:
        with ServeClient(port=server.port) as client:
            client.ingest(vecs)
            client.query(q)
            res = client.topk(q, k=3, mode="scan")
            assert isinstance(res["scores"], np.ndarray)
            assert isinstance(res["labels"], np.ndarray)
            assert res["scores"].shape == (1, 3)
            assert res["scores"][0, 0] == PARAMS.n_hashes
            assert len(res["ids"][0][0]) == 32
            assert res["generation"] >= 1
            with pytest.raises(ServeError):
                client.topk(q, k=3, mode="bogus")
            st = client.status()
            lbv = st["latency_by_verb"]
            assert lbv["topk"]["count"] == 1
            assert lbv["query"]["count"] == 1
            assert lbv["ingest"]["count"] >= 1
            # the flat summary keys ride along for the bench schema
            assert st["serve_topk_count"] == 1
    finally:
        server.shutdown()
        server.server_close()
        d.stop(commit=False)
