"""SIGKILL chaos tests (ISSUE satellite): a subprocess running the
production checkpoint paths is hard-killed mid-write by the fault plane
(``kind=kill`` at a checkpoint site via TSE1M_FAULT_PLAN), then resumed
without the plan.  The resumed run must produce byte-identical output to
an uninterrupted run — including when a shard file was additionally torn
(truncated) on disk between the kill and the resume.
"""

from __future__ import annotations

import glob
import os
import signal
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from tse1m_tpu.resilience import FaultPlan, FaultRule

DRIVER = os.path.join(os.path.dirname(__file__), "chaos_drivers.py")


def run_driver(args, fault_plan_path=None, expect_kill=False):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("TSE1M_FAULT_PLAN", None)
    if fault_plan_path:
        env["TSE1M_FAULT_PLAN"] = fault_plan_path
    proc = subprocess.run([sys.executable, DRIVER, *args], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd="/root/repo")
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, got rc={proc.returncode}\n"
            f"stderr: {proc.stderr[-2000:]}")
    else:
        assert proc.returncode == 0, proc.stderr[-2000:]
    return proc


def test_csv_checkpointer_sigkill_resume_equals_uninterrupted(tmp_path):
    # Uninterrupted oracle.
    clean_dir = str(tmp_path / "clean")
    clean_final = str(tmp_path / "clean.csv")
    run_driver(["csv", "--dir", clean_dir, "--final", clean_final])

    # Chaos run: SIGKILL during the 3rd batch write (tmp written, not yet
    # renamed) — batches 1-2 are durable, batch 3 is a torn tmp file.
    plan_path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="checkpoint.csv.flush", kind="kill",
                         after_calls=2)]).save(plan_path)
    chaos_dir = str(tmp_path / "chaos")
    chaos_final = str(tmp_path / "chaos.csv")
    run_driver(["csv", "--dir", chaos_dir, "--final", chaos_final],
               fault_plan_path=plan_path, expect_kill=True)
    assert not os.path.exists(chaos_final)
    assert len(glob.glob(os.path.join(chaos_dir, "chaos_batch_*.csv"))) == 2
    assert glob.glob(os.path.join(chaos_dir, "*.tmp"))  # the torn write

    # Resume without the plan: re-emits only non-durable ids, merges.
    run_driver(["csv", "--dir", chaos_dir, "--final", chaos_final])
    resumed = pd.read_csv(chaos_final)
    pd.testing.assert_frame_equal(resumed, pd.read_csv(clean_final))
    # the torn tmp never leaked into the merge, and cleanup swept it
    assert not glob.glob(os.path.join(chaos_dir, "*.tmp"))


def test_cluster_checkpoint_sigkill_resume_equals_uninterrupted(tmp_path):
    clean_out = str(tmp_path / "clean.npy")
    run_driver(["cluster", "--dir", str(tmp_path / "ck_clean"),
                "--out", clean_out])
    want = np.load(clean_out)

    plan_path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="checkpoint.cluster.save", kind="kill",
                         after_calls=2)]).save(plan_path)
    ck_dir = str(tmp_path / "ck_chaos")
    out = str(tmp_path / "chaos.npy")
    run_driver(["cluster", "--dir", ck_dir, "--out", out],
               fault_plan_path=plan_path, expect_kill=True)
    assert not os.path.exists(out)
    shards = sorted(s for s in glob.glob(os.path.join(ck_dir, "shard_*.npz"))
                    if not s.endswith(".tmp.npz"))
    assert len(shards) == 2  # two durable chunks before the kill

    # Torn-shard case: truncate one durable shard on disk (the npz is now
    # unreadable) — resume must detect it and recompute that chunk too.
    with open(shards[1], "rb+") as f:
        f.truncate(os.path.getsize(shards[1]) // 2)

    run_driver(["cluster", "--dir", ck_dir, "--out", out])
    np.testing.assert_array_equal(np.load(out), want)
    # successful resume cleaned the checkpoint directory
    assert not glob.glob(os.path.join(ck_dir, "shard_*"))


def test_cluster_overlap_sigkill_resume_equals_uninterrupted(tmp_path):
    """SIGKILL mid-stream under the DOUBLE-BUFFERED path (producer thread
    packing/transferring chunk k+1 while chunk k computes and its shard
    saves): resume must land on labels identical to a sequential
    (--no-overlap) uninterrupted run.  The kill fires during the 3rd of 4
    shard saves, i.e. while the producer thread has the final chunk's
    pack + device_put in flight."""
    import json

    clean_out = str(tmp_path / "clean.npy")
    run_driver(["cluster", "--dir", str(tmp_path / "ck_clean"),
                "--out", clean_out, "--no-overlap"])
    want = np.load(clean_out)

    plan_path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="checkpoint.cluster.save", kind="kill",
                         after_calls=2)]).save(plan_path)
    ck_dir = str(tmp_path / "ck_chaos")
    out = str(tmp_path / "chaos.npy")
    run_driver(["cluster", "--dir", ck_dir, "--out", out],
               fault_plan_path=plan_path, expect_kill=True)
    assert not os.path.exists(out)
    shards = [s for s in glob.glob(os.path.join(ck_dir, "shard_*.npz"))
              if not s.endswith(".tmp.npz")]
    assert len(shards) == 2  # two durable chunks before the kill

    info_path = str(tmp_path / "info.json")
    run_driver(["cluster", "--dir", ck_dir, "--out", out,
                "--info", info_path])
    np.testing.assert_array_equal(np.load(out), want)
    # the resumed overlapped run reported its per-stage telemetry
    info = json.load(open(info_path))
    stages = info["stages"]
    for key in ("stage_encode_s", "stage_h2d_s", "stage_compute_s",
                "h2d_overlap_fraction"):
        assert key in stages, stages


def test_store_shard_sigkill_resume_equals_uninterrupted(tmp_path):
    """SIGKILL mid signature-store shard write (cluster/store.py, site
    ``store.sig.save``: temp files written, not yet renamed/committed):
    the next run must see no committed shard, sweep the torn temps,
    recompute, and land on labels identical to an uninterrupted run —
    including when a COMMITTED shard is additionally truncated on disk
    afterwards (mirroring cluster/checkpoint.py's torn-shard handling)."""
    import json

    clean_out = str(tmp_path / "clean.npy")
    run_driver(["store", "--store-dir", str(tmp_path / "store_clean"),
                "--out", clean_out])
    want = np.load(clean_out)

    plan_path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="store.sig.save", kind="kill")]).save(plan_path)
    store_dir = str(tmp_path / "store_chaos")
    out = str(tmp_path / "chaos.npy")
    run_driver(["store", "--store-dir", store_dir, "--out", out],
               fault_plan_path=plan_path, expect_kill=True)
    assert not os.path.exists(out)
    # the torn write is visible (temps), but no shard was committed
    assert glob.glob(os.path.join(store_dir, "*.tmp.npy"))
    with open(os.path.join(store_dir, "store_manifest.json")) as f:
        assert json.load(f)["shards"] == []

    # Resume without the plan: populate completes; torn temps are swept.
    info_path = str(tmp_path / "info.json")
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--info", info_path])
    np.testing.assert_array_equal(np.load(out), want)
    assert not glob.glob(os.path.join(store_dir, "*.tmp.npy"))

    # Warm re-run hits the cache and merges.
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--info", info_path])
    info = json.load(open(info_path))
    assert info["cache_mode"] == "merge" and info["cache_hit_rate"] > 0.9

    # Torn committed shard: truncate it on disk — the next run must
    # detect the unreadable shard, drop it, recompute its rows, and
    # still produce identical labels.
    shard = sorted(glob.glob(os.path.join(store_dir, "sig_*.npy")))[0]
    with open(shard, "rb+") as f:
        f.truncate(os.path.getsize(shard) // 2)
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--info", info_path])
    np.testing.assert_array_equal(np.load(out), want)


def test_store_state_sigkill_falls_back_to_cached_sigs(tmp_path):
    """SIGKILL mid LSH-state commit (site ``store.state.save``): the
    signature shards are already durable, so the next run starts with a
    full signature cache but no mergeable state — it must take the
    union path on cached signatures and produce identical labels."""
    import json

    clean_out = str(tmp_path / "clean.npy")
    run_driver(["store", "--store-dir", str(tmp_path / "store_clean"),
                "--out", clean_out])
    want = np.load(clean_out)

    plan_path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="store.state.save",
                         kind="kill")]).save(plan_path)
    store_dir = str(tmp_path / "store_chaos")
    out = str(tmp_path / "chaos.npy")
    run_driver(["store", "--store-dir", store_dir, "--out", out],
               fault_plan_path=plan_path, expect_kill=True)
    assert not os.path.exists(out)
    # shards committed before the kill...
    assert glob.glob(os.path.join(store_dir, "sig_*.npy"))
    # ...but no state was.
    assert not os.path.exists(os.path.join(store_dir, "state.json"))

    info_path = str(tmp_path / "info.json")
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--info", info_path])
    np.testing.assert_array_equal(np.load(out), want)
    info = json.load(open(info_path))
    assert info["cache_mode"] == "union" and info["cache_hit_rate"] > 0.9

    # with the state now committed, the next run merges
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--info", info_path])
    np.testing.assert_array_equal(np.load(out), want)
    assert json.load(open(info_path))["cache_mode"] == "merge"


@pytest.mark.slow
def test_cluster_sigkill_twice_then_resume(tmp_path):
    """Two consecutive kills at different chunks, then a clean resume —
    the accumulated-shards path, closer to a flaky long-run reality."""
    clean_out = str(tmp_path / "clean.npy")
    run_driver(["cluster", "--dir", str(tmp_path / "ck_clean"),
                "--out", clean_out])

    ck_dir = str(tmp_path / "ck")
    out = str(tmp_path / "out.npy")
    for after in (1, 2):
        plan_path = str(tmp_path / f"plan{after}.json")
        FaultPlan([FaultRule(site="checkpoint.cluster.save", kind="kill",
                             after_calls=after)]).save(plan_path)
        run_driver(["cluster", "--dir", ck_dir, "--out", out],
                   fault_plan_path=plan_path, expect_kill=True)
    run_driver(["cluster", "--dir", ck_dir, "--out", out])
    np.testing.assert_array_equal(np.load(out), np.load(clean_out))


def test_store_compaction_sigkill_sweeps_temps_and_keeps_parity(tmp_path):
    """SIGKILL mid-compaction (site ``store.compact.save``: the folded
    temps are written, the manifest commit has not happened): the old
    shards stay authoritative, the next OPEN sweeps the stranded temps
    (the on-open orphan sweep — a crashed compaction must not leak disk
    across runs), warm labels match the uninterrupted run, and a retried
    compaction completes with the merge path intact."""
    import json

    store_dir = str(tmp_path / "store")
    out = str(tmp_path / "labels.npy")
    # two corpora -> two committed shards (the compaction work-list)
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--seed", "13"])
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--seed", "29"])
    want = np.load(out)
    with open(os.path.join(store_dir, "store_manifest.json")) as f:
        shards_before = json.load(f)["shards"]
    assert len(shards_before) >= 2

    plan_path = str(tmp_path / "plan.json")
    FaultPlan([FaultRule(site="store.compact.save",
                         kind="kill")]).save(plan_path)
    run_driver(["compact", "--store-dir", store_dir],
               fault_plan_path=plan_path, expect_kill=True)
    # the kill left compacted temps behind, manifest untouched
    assert glob.glob(os.path.join(store_dir, "*.tmp.npy"))
    with open(os.path.join(store_dir, "store_manifest.json")) as f:
        assert json.load(f)["shards"] == shards_before

    # resume: open sweeps the temps; the warm run merges with parity
    info_path = str(tmp_path / "info.json")
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--seed", "29", "--info", info_path])
    np.testing.assert_array_equal(np.load(out), want)
    assert not glob.glob(os.path.join(store_dir, "*.tmp.npy"))
    info = json.load(open(info_path))
    assert info["cache_mode"] == "merge" and info["cache_hit_rate"] > 0.99

    # a retried compaction completes and the merge path survives it
    run_driver(["compact", "--store-dir", store_dir])
    with open(os.path.join(store_dir, "store_manifest.json")) as f:
        assert len(json.load(f)["shards"]) == 1
    run_driver(["store", "--store-dir", store_dir, "--out", out,
                "--seed", "29", "--info", info_path])
    np.testing.assert_array_equal(np.load(out), want)
    assert json.load(open(info_path))["cache_mode"] == "merge"
