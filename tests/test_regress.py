"""Bench-key schema + perf-regression gate (observability/regress.py).

The edge-case contract the module docstring promises, plus the two
acceptance shapes from the graftprof PR: a planted 2x stage-wall
regression at seconds scale MUST fail the gate, and the r08 -> r09
diff must render a readable grouped report.  The committed
``BENCH_r08.json`` / ``BENCH_r09.json`` rounds are the fixtures — the
gate is tested against the artifacts it exists to judge.
"""

from __future__ import annotations

import json
import math
import os

import pytest

from tse1m_tpu.bench import main as bench_main
from tse1m_tpu.observability.regress import (BENCH_SCHEMA,
                                             assert_bench_keys, diff,
                                             format_gate_report, gate,
                                             gated_keys, load_runs,
                                             required_keys,
                                             write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round(name: str) -> dict:
    with open(os.path.join(REPO, name)) as f:
        return json.load(f)


# -- schema contract ----------------------------------------------------------

def test_schema_contexts_cover_the_four_smokes():
    for ctx in ("bench", "degradation", "fault", "serve"):
        assert required_keys(ctx), ctx
    # the graftprof keys joined the serve contract
    serve = required_keys("serve")
    for key in ("serve_unprofiled_p99_ms", "serve_profiled_p99_ms",
                "serve_lock_wait_sites", "serve_slow_requests"):
        assert key in serve, key


def test_assert_bench_keys_names_the_offending_key():
    good = {k: 1 for k in required_keys("serve")}
    assert_bench_keys(good, "serve")  # complete contract passes
    del good["serve_p99_ms"]
    with pytest.raises(AssertionError, match="serve_p99_ms"):
        assert_bench_keys(good, "serve")


def test_gated_keys_are_schema_entries_with_bands():
    keys = gated_keys()
    assert "stage_compute_s" in keys and "ari_vs_planted" in keys
    for key in keys:
        spec = BENCH_SCHEMA[key]
        assert spec["dir"] in ("lower", "higher"), key
        assert spec["tol"] >= 0 and spec["abs"] >= 0, key


def test_committed_rounds_satisfy_the_bench_contract():
    # the schema is derived FROM the trajectory: r08 (the last round
    # that ran the full matrix, warm store included) carries every
    # bench-context key.  r09 skipped the warm-store pass, which is
    # exactly the kind of contract drift assert_bench_keys exists to
    # catch in CI — so it doubles as the negative fixture here.
    assert_bench_keys(_round("BENCH_r08.json"), "bench")
    with pytest.raises(AssertionError, match="cluster_warm_wall_s|cache_"):
        assert_bench_keys(_round("BENCH_r09.json"), "bench")


# -- the gate: clean pass and planted regression ------------------------------

def test_gate_clean_pass_against_own_baseline():
    # an unregressed round gates green; keys with no baseline history
    # (serve_p99_ms is absent from r09's matrix) warn instead of fail
    r09 = _round("BENCH_r09.json")
    report = gate(r09, [r09])
    assert report["ok"], format_gate_report(report)
    r08 = _round("BENCH_r08.json")
    assert gate(r08, [r08])["ok"]


def test_gate_fails_planted_2x_stage_wall():
    """The acceptance criterion: double a stage wall at seconds scale
    against an r09-derived baseline and the gate MUST go red."""
    r09 = _round("BENCH_r09.json")
    assert r09["stage_compute_s"] > 1.0, (
        "fixture rot: planted 2x below seconds scale would hide in the "
        "absolute slack band")
    planted = dict(r09)
    planted["stage_compute_s"] = r09["stage_compute_s"] * 2.0
    report = gate(planted, [r09])
    assert not report["ok"]
    bad = [r for r in report["rows"] if not r["ok"]]
    assert [r["key"] for r in bad] == ["stage_compute_s"]
    assert "stage_compute_s" in format_gate_report(report)


def test_gate_direction_aware_quality_drop():
    r09 = _round("BENCH_r09.json")
    dropped = dict(r09)
    dropped["ari_vs_planted"] = r09["ari_vs_planted"] - 0.2
    report = gate(dropped, [r09])
    assert not report["ok"]
    bad = {r["key"] for r in report["rows"] if not r["ok"]}
    assert bad == {"ari_vs_planted"}
    # a quality IMPROVEMENT never trips a lower-is-better style bound
    improved = dict(r09)
    improved["ari_vs_planted"] = min(1.0, r09["ari_vs_planted"] + 0.1)
    assert gate(improved, [r09])["ok"]


def test_gate_missing_key_current_fails_missing_baseline_warns():
    r09 = _round("BENCH_r09.json")
    # gated key missing from the CURRENT run: the contract shrank — red
    shrunk = {k: v for k, v in r09.items() if k != "stage_compute_s"}
    report = gate(shrunk, [r09])
    assert not report["ok"]
    row = next(r for r in report["rows"] if r["key"] == "stage_compute_s")
    assert "contract shrank" in row["note"]
    # gated key missing from the BASELINE: the contract grew — warn only
    baseline = {k: v for k, v in r09.items() if k != "stage_compute_s"}
    report = gate(r09, [baseline])
    assert report["ok"]
    row = next(r for r in report["rows"] if r["key"] == "stage_compute_s")
    assert row["ok"] and "re-baseline" in row["note"]


def test_gate_zero_and_nan_baselines_never_crash():
    base = {"stage_compute_s": 0.0}
    # zero median: band degrades to 3*MAD + abs slack (0.5 s here)
    assert gate({"stage_compute_s": 0.4}, [base],
                keys=("stage_compute_s",))["ok"]
    assert not gate({"stage_compute_s": 0.9}, [base],
                    keys=("stage_compute_s",))["ok"]
    # NaN baseline values are filtered; with no finite history the key
    # is reported, not gated
    report = gate({"stage_compute_s": 5.0},
                  [{"stage_compute_s": float("nan")}],
                  keys=("stage_compute_s",))
    assert report["ok"]
    assert "no baseline history" in report["rows"][0]["note"]
    # NaN CURRENT value: skipped with a note, never a crash
    report = gate({"stage_compute_s": float("nan")},
                  [{"stage_compute_s": 2.0}], keys=("stage_compute_s",))
    assert report["ok"]
    assert "non-finite" in report["rows"][0]["note"]


def test_gate_single_run_baseline_has_no_mad_term():
    report = gate({"stage_compute_s": 2.0}, [{"stage_compute_s": 2.0}],
                  keys=("stage_compute_s",))
    row = report["rows"][0]
    assert row["ok"] and row["mad"] == 0.0 and row["n"] == 1
    assert "single-run" in row["note"]


def test_gate_mad_widens_band_with_noisy_history():
    # history median 2.0, MAD 0.5: bound = 2 + 2*0.75 + 3*0.5 + 0.5 = 5.5
    hist = [{"stage_compute_s": v} for v in (1.5, 2.0, 2.5)]
    assert gate({"stage_compute_s": 5.4}, hist,
                keys=("stage_compute_s",))["ok"]
    assert not gate({"stage_compute_s": 5.6}, hist,
                    keys=("stage_compute_s",))["ok"]


# -- the diff -----------------------------------------------------------------

def test_diff_r08_r09_is_readable():
    out = diff(_round("BENCH_r08.json"), _round("BENCH_r09.json"),
               name_a="BENCH_r08.json", name_b="BENCH_r09.json")
    assert out.startswith("bench diff: BENCH_r08.json -> BENCH_r09.json")
    # grouped sections, and the serve keys that arrived in r09 are
    # listed as a visible contract change, not silently dropped
    assert "[stage]" in out or "[core]" in out
    assert "only in BENCH_r09.json" in out


def test_diff_direction_aware_verdicts():
    a = {"stage_compute_s": 2.0, "ari_vs_planted": 0.9,
         "cluster_encoding": "delta-v3"}
    b = {"stage_compute_s": 4.0, "ari_vs_planted": 0.99,
         "cluster_encoding": "delta-v4"}
    out = diff(a, b)
    assert "WORSE" in out      # wall doubled (lower is better)
    assert "better" in out     # quality rose (higher is better)
    assert "'delta-v3' -> 'delta-v4'" in out  # identity change shown


def test_diff_flags_scale_change_and_zero_to_zero():
    a = {"metric": "2k", "stage_compute_s": 0.0}
    b = {"metric": "1m", "stage_compute_s": 1.0}
    out = diff(a, b, show_all=True)
    assert "not scale-comparable" in out
    assert "new" in out  # zero -> nonzero renders, no ZeroDivisionError
    # identical ungated values are suppressed by default
    assert "(no differences)" in diff({"x": 1}, {"x": 1})


# -- baseline files + module CLI ----------------------------------------------

def test_write_and_load_baseline_roundtrip(tmp_path):
    runs = [{"stage_compute_s": v} for v in (1.0, 2.0, 3.0)]
    path = str(tmp_path / "baseline.json")
    write_baseline(path, runs, note="test history")
    loaded = load_runs(path)
    assert loaded == runs
    # a bare single-result file loads as a one-run history
    single = str(tmp_path / "single.json")
    with open(single, "w") as f:
        json.dump({"stage_compute_s": 2.0}, f)
    assert load_runs(single) == [{"stage_compute_s": 2.0}]
    with open(single, "w") as f:
        json.dump([], f)
    with pytest.raises(ValueError):
        load_runs(single)


def test_bench_cli_gate_exit_codes(tmp_path, capsys):
    r09 = os.path.join(REPO, "BENCH_r09.json")
    base = str(tmp_path / "base.json")
    assert bench_main(["baseline", base, r09, "--note", "r09"]) == 0
    assert bench_main(["gate", r09, "--baseline", base]) == 0
    planted = dict(_round("BENCH_r09.json"))
    planted["stage_compute_s"] *= 2.0
    cur = str(tmp_path / "planted.json")
    with open(cur, "w") as f:
        json.dump(planted, f)
    assert bench_main(["gate", cur, "--baseline", base]) == 1
    out = capsys.readouterr().out
    assert "perf gate: PASS" in out and "perf gate: FAIL" in out
    assert "stage_compute_s" in out


def test_bench_cli_diff_and_keys(capsys):
    r08 = os.path.join(REPO, "BENCH_r08.json")
    r09 = os.path.join(REPO, "BENCH_r09.json")
    assert bench_main(["diff", r08, r09]) == 0
    assert bench_main(["keys", "serve"]) == 0
    out = capsys.readouterr().out
    assert "bench diff:" in out
    assert "serve_profiled_p99_ms" in out


@pytest.mark.parametrize("make", [
    lambda p: p,                                        # missing
    lambda p: (open(p, "w").close(), p)[1],             # truncated/empty
    lambda p: (open(p, "w").write('{"broken'), p)[1],   # corrupt JSON
])
def test_bench_cli_bad_inputs_one_line_error(tmp_path, capsys, make):
    """diff/gate on a missing, truncated or corrupt file must print ONE
    actionable line (the path + the `bench baseline` remint hint) on
    stderr and exit nonzero — never a traceback."""
    bad = make(str(tmp_path / "bad.json"))
    good = os.path.join(REPO, "BENCH_r09.json")
    assert bench_main(["diff", bad, good]) == 2
    assert bench_main(["diff", good, bad]) == 2
    assert bench_main(["gate", bad, "--baseline", good]) == 2
    assert bench_main(["gate", good, "--baseline", bad]) == 2
    err = capsys.readouterr().err
    lines = [ln for ln in err.splitlines() if ln.strip()]
    assert len(lines) == 4
    for ln in lines:
        assert ln.startswith("bench: cannot read ")
        assert bad in ln
        assert "tse1m_tpu.bench baseline" in ln


def test_committed_smoke_baseline_is_loadable():
    runs = load_runs(os.path.join(REPO, "BENCH_baseline_smoke.json"))
    assert runs
    for run in runs:
        assert math.isfinite(float(run["value"]))
