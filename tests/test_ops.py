"""Device segment ops vs numpy oracles."""

import numpy as np
import pytest

from tse1m_tpu.ops.segment import (counts_to_survival, masked_percentile,
                                   segment_searchsorted,
                                   unique_pairs_count_per_iteration)


@pytest.mark.parametrize("side", ["left", "right"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_segment_searchsorted_matches_numpy(side, seed):
    r = np.random.default_rng(seed)
    P = 9
    counts = r.integers(0, 40, size=P)  # include empty segments
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    values = np.concatenate([np.sort(r.integers(0, 1000, size=c)) for c in counts]) \
        if counts.sum() else np.empty(0, np.int64)
    Q = 200
    qseg = r.integers(0, P, size=Q)
    queries = r.integers(-10, 1010, size=Q)

    got = np.asarray(segment_searchsorted(values.astype(np.int32), offsets,
                                          queries.astype(np.int32), qseg, side=side))
    want = np.array([
        np.searchsorted(values[offsets[s]:offsets[s + 1]], q, side=side)
        for s, q in zip(qseg, queries)
    ])
    np.testing.assert_array_equal(got, want)


def test_segment_searchsorted_empty_values():
    out = segment_searchsorted(np.empty(0, np.int32), np.zeros(4, np.int64),
                               np.array([5, 7], np.int32), np.array([0, 2]))
    np.testing.assert_array_equal(np.asarray(out), [0, 0])


def test_counts_to_survival():
    counts = np.array([3, 1, 5, 0, 3])
    got = np.asarray(counts_to_survival(counts, 5))
    # k=1: 4 segments with >=1; k=2: 3; k=3: 3; k=4: 1; k=5: 1
    np.testing.assert_array_equal(got, [4, 3, 3, 1, 1])


def test_unique_pairs_count():
    segs = np.array([0, 0, 1, 2, 2, 2, 1])
    iters = np.array([1, 1, 1, 2, 2, 9, 0])  # 9 > max_k ignored; 0 ignored
    got = np.asarray(unique_pairs_count_per_iteration(segs, iters, 3, 4))
    # iter1: segments {0,1} -> 2; iter2: {2} -> 1
    np.testing.assert_array_equal(got, [2, 1, 0, 0])


@pytest.mark.parametrize("q", [25.0, 50.0, 75.0, 90.0])
def test_masked_percentile_matches_numpy(q, rng):
    R, C = 12, 50
    x = rng.normal(size=(R, C)).astype(np.float32)
    mask = rng.random((R, C)) < 0.7
    mask[3] = False  # fully-masked row
    got = np.asarray(masked_percentile(x, mask, q))
    for i in range(R):
        if mask[i].sum() == 0:
            assert np.isnan(got[i])
        else:
            np.testing.assert_allclose(got[i], np.percentile(x[i][mask[i]], q),
                                       rtol=1e-5)


def test_masked_percentile_vector_q(rng):
    x = rng.normal(size=(4, 20)).astype(np.float32)
    mask = np.ones_like(x, dtype=bool)
    got = np.asarray(masked_percentile(x, mask, np.array([25.0, 75.0])))
    assert got.shape == (2, 4)
    np.testing.assert_allclose(got[0], np.percentile(x, 25, axis=1), rtol=1e-5)
