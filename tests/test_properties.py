"""Property-based tests (hypothesis) for the pure kernels whose edge cases
are too numerous to enumerate by hand: the native ISO parser vs the pandas
oracle, the pg-array literal round-trip, rev_hash invariances, the
segment-searchsorted device op vs numpy per segment, and the buildlog
fetch window's ordering guarantee.
"""

from __future__ import annotations

import os
import sqlite3

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

pytest.importorskip(
    "hypothesis",
    reason="property tests need hypothesis (pip install -e .[test])")
from hypothesis import given, settings, strategies as st  # noqa: E402

from tse1m_tpu.cluster import adjusted_rand_index  # noqa: F401 (env check)
from tse1m_tpu.collect.buildlogs import _windowed_map
from tse1m_tpu.data.columnar import rev_hash
from tse1m_tpu.db.ingest import parse_array, pg_array_literal
from tse1m_tpu.ops.segment import segment_searchsorted


# -- native ISO parser vs pandas ----------------------------------------------

def _native_available():
    from tse1m_tpu import native

    return native._load() is not None


timestamps = st.datetimes(
    min_value=pd.Timestamp("1700-01-01").to_pydatetime(),
    max_value=pd.Timestamp("2200-12-31").to_pydatetime())


@pytest.mark.skipif(not _native_available(), reason="native unavailable")
@settings(max_examples=60, deadline=None)
@given(st.lists(timestamps, min_size=1, max_size=8),
       st.sampled_from(["%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S",
                        "%Y-%m-%d"]),
       st.integers(min_value=0, max_value=9))
def test_native_iso_parse_matches_pandas(tmp_path_factory, dts, fmt, frac):
    from tse1m_tpu.native import fetch_table

    texts = []
    for dt in dts:
        s = dt.strftime(fmt)
        if frac and "%H" in fmt:
            digits = str(dt.microsecond).zfill(6)[:frac].ljust(frac, "0")
            s += "." + digits
        texts.append(s)
    d = tmp_path_factory.mktemp("prop_iso")
    p = str(d / "t.sqlite")
    con = sqlite3.connect(p)
    con.execute("CREATE TABLE t (ts TEXT)")
    con.executemany("INSERT INTO t VALUES (?)", [(s,) for s in texts])
    con.commit()
    con.close()
    (got,) = fetch_table(p, "SELECT ts FROM t", (), "t", [])
    exp = (pd.to_datetime(pd.Series(texts), format="ISO8601").to_numpy()
           .astype("datetime64[ns]").astype(np.int64))
    os.unlink(p)
    np.testing.assert_array_equal(got, exp)


# -- pg array literal round-trip ----------------------------------------------

array_items = st.lists(
    st.text(alphabet=st.characters(blacklist_categories=("Cs",)),
            max_size=30),
    max_size=6)


@settings(max_examples=200, deadline=None)
@given(array_items)
def test_pg_array_literal_roundtrip(items):
    lit = pg_array_literal(items)
    assert parse_array(lit) == [str(i) for i in items]


# -- rev_hash invariances -----------------------------------------------------

@settings(max_examples=100, deadline=None)
@given(st.lists(st.text(alphabet="abcdef0123456789", min_size=1,
                        max_size=12), min_size=1, max_size=8))
def test_rev_hash_order_invariant_and_nonnegative(revs):
    rng = np.random.default_rng(1)
    shuffled = list(revs)
    rng.shuffle(shuffled)
    assert rev_hash(revs) == rev_hash(shuffled)  # set semantics (rq3:280)
    assert rev_hash(revs) >= 0                   # 63-bit


@settings(max_examples=60, deadline=None)
@given(st.lists(st.text(min_size=1, max_size=8), min_size=1, max_size=5),
       st.text(min_size=1, max_size=8))
def test_rev_hash_sensitive_to_membership(revs, extra):
    if extra in revs:
        revs = [r for r in revs if r != extra] or ["x"]
        if extra in revs:
            return
    assert rev_hash(revs) != rev_hash(revs + [extra])


# -- segment_searchsorted vs numpy per segment --------------------------------

@settings(max_examples=60, deadline=None)
@given(st.data())
def test_segment_searchsorted_matches_numpy(data):
    rng_seed = data.draw(st.integers(0, 2**31 - 1))
    rng = np.random.default_rng(rng_seed)
    P = data.draw(st.integers(1, 5))
    counts = rng.integers(0, 12, size=P)
    off = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    vals = rng.integers(-50, 50, size=int(off[-1])).astype(np.int32)
    vals = np.concatenate(
        [np.sort(vals[a:b]) for a, b in zip(off, off[1:])]) if off[-1] \
        else vals
    q = data.draw(st.integers(1, 16))
    seg = rng.integers(0, P, size=q).astype(np.int32)
    queries = rng.integers(-60, 60, size=q).astype(np.int32)
    side = data.draw(st.sampled_from(["left", "right"]))
    got = np.asarray(segment_searchsorted(
        jnp.asarray(vals), jnp.asarray(off, jnp.int32),
        jnp.asarray(queries), jnp.asarray(seg), side=side))
    exp = np.array([
        np.searchsorted(vals[off[s]:off[s + 1]], qv, side=side)
        for s, qv in zip(seg, queries)], dtype=np.int32)
    np.testing.assert_array_equal(got, exp)


# -- windowed map ordering ----------------------------------------------------

@settings(max_examples=50, deadline=None)
@given(st.lists(st.integers(0, 1000), max_size=40),
       st.integers(1, 10))
def test_windowed_map_preserves_order(items, window):
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(4) as pool:
        got = list(_windowed_map(pool, lambda x: x * 2, items, window))
    assert got == [x * 2 for x in items]


# -- lazy/coded column wrappers ----------------------------------------------

# NUL is excluded for the CodedColumn fallback's sake: pandas 3.0's
# factorize conflates '' with '\x00' (its object hash table treats them as
# one key), which would fail the round-trip below inside pandas, not in
# our wrappers.  Study text (project names, results, module lists) never
# carries NUL; BytesColumn handles it fine either way.
_text_cells = st.lists(
    st.one_of(st.none(),
              st.text(st.characters(exclude_characters="\x00",
                                    exclude_categories=("Cs",)),
                      min_size=0, max_size=24)),
    min_size=0, max_size=64)


@settings(max_examples=60, deadline=None)
@given(cells=_text_cells, data=st.data())
def test_bytes_column_roundtrip_and_indexing(cells, data):
    """BytesColumn.from_objects must be a lossless lazy view: scalar access
    reproduces every cell (incl None and empty/unicode strings), and
    slice/fancy indexing commutes with materialisation."""
    from tse1m_tpu.data.columnar import BytesColumn

    col = BytesColumn.from_objects(cells)
    assert len(col) == len(cells)
    for i, v in enumerate(cells):
        assert col[i] == v
    if cells:
        idx = np.asarray(
            data.draw(st.lists(st.integers(0, len(cells) - 1),
                               min_size=0, max_size=8)), dtype=np.int64)
        sub = col[idx]
        for k, i in enumerate(idx):
            assert sub[k] == cells[int(i)]
        np.testing.assert_array_equal(
            col[1:].materialize(),
            np.array(cells[1:], dtype=object))


@settings(max_examples=60, deadline=None)
@given(cells=_text_cells)
def test_coded_column_matches_factorize_semantics(cells):
    """CodedColumn built the fallback way (factorize) must reproduce every
    cell through scalar access and materialize(), with NULL as code -1."""
    from tse1m_tpu.data.columnar import CodedColumn

    ser = pd.Series(cells, dtype=object)
    codes, uniq = pd.factorize(ser, use_na_sentinel=True)
    col = CodedColumn(codes, np.asarray(uniq, dtype=object))
    assert len(col) == len(cells)
    for i, v in enumerate(cells):
        assert col[i] == v
    np.testing.assert_array_equal(col.materialize(),
                                  np.array(cells, dtype=object))
    assert ((col.codes == -1) == np.array([c is None for c in cells])).all()
