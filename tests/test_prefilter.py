"""Wire v3 host-side LSH prefilter (cluster/prefilter.py + pipeline).

The contract under test: prefiltered labels equal the unfiltered run's
ELEMENTWISE (ARI 1.0 is implied), across encodings, quantization, the
checkpointed resume, and the degradation rungs; the filter never drops a
member of a planted multi-row cluster (recall 1.0); and the escape
hatch (`ClusterParams.prefilter = off|auto|on`) refuses the
combinations whose semantics it cannot honor.
"""

from __future__ import annotations

import os
from dataclasses import replace

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tse1m_tpu.cluster import (ClusterParams, cluster_sessions,  # noqa: E402
                               cluster_sessions_resumable)
from tse1m_tpu.cluster import pipeline as pipeline_mod  # noqa: E402
from tse1m_tpu.cluster import prefilter as pf  # noqa: E402
from tse1m_tpu.cluster.pipeline import last_run_info  # noqa: E402
from tse1m_tpu.data.synth import synth_session_sets  # noqa: E402
from tse1m_tpu.observability import pop_degradation_events  # noqa: E402
from tse1m_tpu.resilience.faults import FaultPlan  # noqa: E402

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False

PARAMS = dict(use_pallas="never")


@pytest.fixture(autouse=True)
def _isolated_calibration(tmp_path, monkeypatch):
    monkeypatch.setenv("TSE1M_ROUTER_CAL",
                       os.path.join(str(tmp_path), "cal.json"))
    pop_degradation_events()
    yield
    pop_degradation_events()


def test_collide_mask_keeps_every_planted_near_duplicate():
    items, truth = synth_session_sets(6000, set_size=64, seed=0)
    keep = pf.collide_mask(items, seed=0)
    assert pf.prefilter_recall(keep, truth) == 1.0
    # the planted workload is 40% singletons — a real fraction must drop
    assert 0.2 < 1.0 - keep.mean() < 0.5


def test_collide_mask_trivial_inputs():
    assert pf.collide_mask(np.zeros((0, 4), np.uint32)).size == 0
    assert not pf.collide_mask(np.ones((1, 4), np.uint32)).any()
    dup = np.tile(np.arange(8, dtype=np.uint32), (2, 1))
    assert pf.collide_mask(dup).all()  # exact duplicates always collide


@pytest.mark.parametrize("encoding", ["pack24", "delta", "auto"])
def test_label_parity_elementwise(encoding):
    items, _ = synth_session_sets(4000, set_size=64, seed=1)
    base = ClusterParams(encoding=encoding, prefilter="off", **PARAMS)
    want = cluster_sessions(items, base)
    got = cluster_sessions(items, replace(base, prefilter="on"))
    np.testing.assert_array_equal(got, want)
    assert last_run_info["prefilter_rows_dropped"] > 0
    assert last_run_info["wire_version"] == 3
    assert last_run_info["wire_v3_saved_mb"] > 0


def test_label_parity_quantized_universe():
    items, _ = synth_session_sets(4000, set_size=64, seed=2)
    base = ClusterParams(wire_quant_bits=10, prefilter="off", **PARAMS)
    want = cluster_sessions(items, base)
    got = cluster_sessions(items, replace(base, prefilter="on"))
    np.testing.assert_array_equal(got, want)


def test_auto_gate_stays_off_below_size_threshold():
    items, _ = synth_session_sets(500, set_size=16, seed=3)
    cluster_sessions(items, ClusterParams(**PARAMS))  # auto default
    assert last_run_info["prefilter_rows_dropped"] == 0
    assert last_run_info["prefilter_hit_rate"] == 0.0


def test_auto_engages_when_size_gate_lowered(monkeypatch):
    monkeypatch.setattr(pipeline_mod, "_AUTO_MIN_BYTES", 1024)
    items, _ = synth_session_sets(2000, set_size=16, seed=4)
    want = cluster_sessions(items, ClusterParams(prefilter="off", **PARAMS))
    got = cluster_sessions(items, ClusterParams(**PARAMS))
    assert last_run_info["prefilter_rows_dropped"] > 0
    np.testing.assert_array_equal(got, want)


def test_escape_hatch_validation():
    items = np.ones((4, 4), np.uint32)
    with pytest.raises(ValueError, match="prefilter"):
        cluster_sessions(items, ClusterParams(prefilter="banana"))
    with pytest.raises(ValueError, match="storeless-only"):
        cluster_sessions(items, ClusterParams(prefilter="on",
                                              sig_store="/tmp/nope"))
    with pytest.raises(ValueError, match="threshold"):
        cluster_sessions(items, ClusterParams(prefilter="on",
                                              threshold=0.0))
    # auto + store: silently off, the store path owns every row
    assert pipeline_mod._prefilter_mask(
        items, ClusterParams(prefilter="auto", sig_store="/tmp/nope")) \
        is None


def test_prefilter_on_under_mesh_refuses():
    items = np.ones((8, 4), np.uint32)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    with pytest.raises(ValueError, match="single-host"):
        cluster_sessions(items, ClusterParams(prefilter="on"), mesh=mesh)


def test_resumable_parity_and_policy_refusal(tmp_path):
    items, _ = synth_session_sets(3000, set_size=64, seed=5)
    base = ClusterParams(prefilter="off", h2d_chunks=2, **PARAMS)
    want = cluster_sessions(items, base)
    d = str(tmp_path / "ck")
    got = cluster_sessions_resumable(items, replace(base, prefilter="on"),
                                     checkpoint_dir=d, cleanup=False)
    np.testing.assert_array_equal(got, want)
    # a resume under a CHANGED prefilter policy holds different rows per
    # shard — it must refuse, not mix
    with pytest.raises(ValueError, match="different run"):
        cluster_sessions_resumable(items, base, checkpoint_dir=d)


def test_resumable_kill_window_resumes_with_prefilter(tmp_path):
    from tse1m_tpu.cluster.checkpoint import ClusterCheckpoint

    items, _ = synth_session_sets(3000, set_size=64, seed=6)
    prm = ClusterParams(prefilter="on", h2d_chunks=3, **PARAMS)
    want = cluster_sessions(items, replace(prm, prefilter="off"))
    d = str(tmp_path / "ck")

    class Boom(RuntimeError):
        pass

    real_save = ClusterCheckpoint.save_chunk
    calls = []

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        calls.append(index)
        if len(calls) == 1:
            raise Boom()

    ClusterCheckpoint.save_chunk = dying_save
    try:
        with pytest.raises(Boom):
            cluster_sessions_resumable(items, prm, checkpoint_dir=d)
    finally:
        ClusterCheckpoint.save_chunk = real_save
    # resume recomputes the same deterministic mask and finishes
    got = cluster_sessions_resumable(items, prm, checkpoint_dir=d)
    np.testing.assert_array_equal(got, want)


def _oom_plan(times: int = 1) -> FaultPlan:
    return FaultPlan.from_dict({"rules": [{
        "site": "pipeline.h2d", "kind": "raise", "times": times,
        "message": "RESOURCE_EXHAUSTED: injected allocation failure"}]})


def test_quant_drop_rung_composes_with_prefilter():
    """RESOURCE_EXHAUSTED under the v3 levers: the quant rung drops the
    width mid-stream; the degraded labels must equal a CLEAN unfiltered
    run at the surviving width (the raw-space mask is width-independent,
    so the restart never invalidates the kept set)."""
    from tse1m_tpu.cluster.pipeline import _restore_quant_bits

    items, _ = synth_session_sets(2000, set_size=16, seed=13)
    prm = ClusterParams(prefilter="on", entropy="force", n_hashes=32,
                        n_bands=4, **PARAMS)
    with _oom_plan().active():
        got = cluster_sessions(items, prm)
    kinds = [e["kind"] for e in pop_degradation_events()]
    assert "quant_drop" in kinds
    _restore_quant_bits()
    want = cluster_sessions(items, ClusterParams(
        wire_quant_bits=10, n_hashes=32, n_bands=4, **PARAMS))
    np.testing.assert_array_equal(got, want)


def test_oom_chunk_halving_reencodes_at_surviving_width():
    """Past the last quant rung, chunk halving re-packs (and, with the
    codec forced, re-ENCODES) from the host buffer at the surviving
    width — labels equal a clean run at the floor width."""
    from tse1m_tpu.cluster.pipeline import _restore_quant_bits

    items, _ = synth_session_sets(2000, set_size=16, seed=13)
    prm = ClusterParams(prefilter="on", entropy="force", h2d_chunks=2,
                        n_hashes=32, n_bands=4, **PARAMS)
    with _oom_plan(times=3).active():
        got = cluster_sessions(items, prm)
    kinds = [e["kind"] for e in pop_degradation_events()]
    assert "chunk_halving" in kinds and "quant_drop" in kinds
    _restore_quant_bits()
    want = cluster_sessions(items, ClusterParams(
        wire_quant_bits=8, n_hashes=32, n_bands=4, **PARAMS))
    np.testing.assert_array_equal(got, want)


def test_v3_hot_loop_sanitizer_clean():
    """Wire v3 keeps the hot-loop guarantees: a warm run with the
    prefilter on and the codec forced performs ZERO implicit
    host->device transfers and ZERO steady-state recompiles (the rANS
    decode jits key on static (n, shift) — same shapes, cache hits)."""
    from tse1m_tpu.lint.runtime import sanitized

    items, _ = synth_session_sets(3000, set_size=64, seed=8)
    prm = ClusterParams(prefilter="on", entropy="force", h2d_chunks=2,
                        **PARAMS)
    warm = cluster_sessions(items, prm)  # compile + stage everything
    with sanitized(compile_budget=0) as report:
        labels = cluster_sessions(items, prm)
    np.testing.assert_array_equal(labels, warm)
    assert report.compile_count == 0
    assert report.transfer_guard_active


def test_wire_payloads_probe_matches_pipeline():
    """The drift-guard contract under wire v3: the probe's byte
    inventory equals the h2d bytes the run records, with the prefilter
    AND the codec engaged."""
    items, _ = synth_session_sets(3000, set_size=64, seed=7)
    for prm in (ClusterParams(prefilter="on", entropy="force",
                              encoding="delta", **PARAMS),
                ClusterParams(prefilter="on", entropy="auto",
                              encoding="pack24", **PARAMS)):
        cluster_sessions(items, prm)
        recorded = last_run_info["wire_bytes"]
        payloads, info = pipeline_mod.wire_payloads(items, prm)
        assert sum(p.nbytes for p in payloads) == recorded
        assert info["wire_version"] == 3
        assert info["prefilter_rows_dropped"] \
            == last_run_info["prefilter_rows_dropped"]


if HAVE_HYPOTHESIS:

    @settings(max_examples=12, deadline=None, derandomize=True)
    @given(st.data())
    def test_parity_property_over_planted_densities(data):
        """Hypothesis-randomized parity: across planted-cluster density
        (dup fraction, cluster size, mutation rate) and universe width,
        prefiltered labels == unfiltered labels elementwise (ARI 1.0)."""
        n = data.draw(st.integers(600, 2500), label="n")
        dup = data.draw(st.floats(0.2, 0.9), label="dup_fraction")
        mean_sz = data.draw(st.floats(2.0, 16.0), label="mean_cluster")
        mut = data.draw(st.floats(0.0, 0.05), label="mutate_prob")
        qbits = data.draw(st.sampled_from([0, 10, 12]), label="qbits")
        seed = data.draw(st.integers(0, 2**16), label="seed")
        items, truth = synth_session_sets(
            n, set_size=32, dup_fraction=dup, mean_cluster_size=mean_sz,
            mutate_prob=mut, seed=seed)
        keep = pf.collide_mask(items, seed=0)
        assert pf.prefilter_recall(keep, truth) == 1.0
        prm = ClusterParams(
            prefilter="off", n_hashes=32, n_bands=4,
            wire_quant_bits=qbits if qbits else -1, **PARAMS)
        want = cluster_sessions(items, prm)
        got = cluster_sessions(items, replace(prm, prefilter="on"))
        np.testing.assert_array_equal(got, want)

else:  # pragma: no cover - environment without hypothesis

    @pytest.mark.skip(reason="property tests need hypothesis "
                             "(pip install tse1m-tpu[test])")
    def test_parity_property_over_planted_densities():
        ...
