"""RQ2: change-point + trend backend parity, oracle semantics, artifacts."""

import os

import numpy as np
import pytest

from tse1m_tpu.analysis.rq2_changepoints import run_rq2_changepoints
from tse1m_tpu.analysis.rq2_trends import run_rq2_trends
from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend, floor_day_ns
from tse1m_tpu.config import Config, RESULT_OK
from tse1m_tpu.data.columnar import StudyArrays

LIMIT = "2026-01-01"


@pytest.fixture(scope="module")
def arrays(study_db):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT)
    return StudyArrays.from_db(study_db, cfg)


@pytest.fixture(scope="module")
def limit_ns():
    return int(np.datetime64(LIMIT, "ns").astype(np.int64))


def test_change_points_backend_parity(arrays, limit_ns):
    pd_res = PandasBackend().rq2_change_points(arrays, limit_ns)
    jx_res = JaxBackend().rq2_change_points(arrays, limit_ns)
    np.testing.assert_array_equal(pd_res.project_idx, jx_res.project_idx)
    np.testing.assert_array_equal(pd_res.end_i, jx_res.end_i)
    np.testing.assert_array_equal(pd_res.start_ip1, jx_res.start_ip1)
    for f in ("covered_i", "total_i", "covered_ip1", "total_ip1"):
        np.testing.assert_array_equal(getattr(pd_res, f), getattr(jx_res, f))
    np.testing.assert_array_equal(pd_res.diff_total_line, jx_res.diff_total_line)
    np.testing.assert_array_equal(pd_res.diff_coverage, jx_res.diff_coverage)
    assert len(pd_res.project_idx) > 0


def test_change_points_oracle(arrays, limit_ns, study_db):
    """Re-derive change points straight from DB rows with the reference's
    pandas shift/cumsum recipe (rq2_coverage_and_added.py:126-166)."""
    import pandas as pd

    res = PandasBackend().rq2_change_points(arrays, limit_ns)
    got = {}
    for k in range(len(res.project_idx)):
        p = arrays.projects[int(res.project_idx[k])]
        got.setdefault(p, []).append(
            (int(arrays.covb.columns["time_ns"][res.end_i[k]]),
             int(arrays.covb.columns["time_ns"][res.start_ip1[k]])))

    for project in arrays.projects:
        rows = study_db.query(
            "SELECT timecreated, modules, revisions FROM buildlog_data "
            "WHERE project = ? AND build_type='Coverage' "
            f"AND result IN {tuple(RESULT_OK)} "
            "AND timecreated < ? ORDER BY timecreated", (project, LIMIT))
        cov = study_db.query(
            "SELECT date FROM total_coverage WHERE project = ? AND date < ?",
            (project, LIMIT))
        if not rows or not cov:
            assert project not in got
            continue
        df = pd.DataFrame(rows, columns=["timecreated", "modules", "revisions"])
        df["key"] = df["modules"].astype(str) + "_" + df["revisions"].astype(str)
        df["gid"] = (df["key"] != df["key"].shift(1)).cumsum()
        groups = df.groupby("gid")
        bounds = [(g.iloc[0]["timecreated"], g.iloc[-1]["timecreated"])
                  for _, g in groups]
        expect = [(pd.Timestamp(bounds[i][1]).value,
                   pd.Timestamp(bounds[i + 1][0]).value)
                  for i in range(len(bounds) - 1)]
        assert got.get(project, []) == expect, project


@pytest.mark.parametrize("mesh", [None, "auto"],
                         ids=["single-device", "mesh"])
def test_trends_backend_parity(arrays, limit_ns, mesh):
    pd_res = PandasBackend().rq2_trends(arrays, limit_ns)
    jx_res = JaxBackend(mesh=mesh).rq2_trends(arrays, limit_ns)
    np.testing.assert_array_equal(pd_res.mask, jx_res.mask)
    np.testing.assert_allclose(pd_res.matrix, jx_res.matrix, equal_nan=True)
    np.testing.assert_array_equal(pd_res.counts, jx_res.counts)
    np.testing.assert_allclose(pd_res.spearman, jx_res.spearman,
                               atol=1e-5, equal_nan=True)
    np.testing.assert_allclose(pd_res.percentiles, jx_res.percentiles,
                               atol=5e-3, equal_nan=True)
    np.testing.assert_allclose(pd_res.mean, jx_res.mean, atol=5e-3,
                               equal_nan=True)
    assert pd_res.matrix.shape[1] >= 365


def test_trends_spearman_matches_scipy(arrays, limit_ns):
    from scipy.stats import spearmanr

    jx_res = JaxBackend().rq2_trends(arrays, limit_ns)
    for p in range(arrays.n_projects):
        t = jx_res.matrix[p, jx_res.mask[p]]
        if len(t) >= 2:
            rho, _ = spearmanr(range(len(t)), t)
            assert abs(jx_res.spearman[p] - rho) < 1e-5


def test_masked_spearman_ties():
    """Tied values must get scipy's average ranks on device."""
    from scipy.stats import spearmanr

    from tse1m_tpu.ops.segment import masked_spearman

    x = np.array([[3.0, 1.0, 1.0, 2.0, 2.0, 2.0, 5.0, 0.0]], dtype=np.float32)
    mask = np.array([[True] * 7 + [False]])
    got = float(np.asarray(masked_spearman(x, mask))[0])
    want, _ = spearmanr(range(7), x[0, :7])
    assert abs(got - want) < 1e-6


def test_floor_day_ns():
    t = int(np.datetime64("2024-05-06T17:33:12", "ns").astype(np.int64))
    d = int(np.datetime64("2024-05-06", "ns").astype(np.int64))
    assert floor_day_ns(np.array([t]))[0] == d


@pytest.mark.parametrize("backend", ["pandas", "jax_tpu", "auto"])
def test_run_rq2_end_to_end(backend, study_db, tmp_path):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT, backend=backend,
                 result_dir=str(tmp_path / backend))
    cfg.min_projects_per_iteration = 2
    out_a = run_rq2_changepoints(cfg, db=study_db)
    assert out_a["merged_csv"] and os.path.exists(out_a["merged_csv"])
    with open(out_a["merged_csv"]) as f:
        header = f.readline().strip()
    assert header.startswith("project,timecreated_i,modules_i")

    out_b = run_rq2_trends(cfg, db=study_db, per_project_figures=False)
    assert os.path.exists(out_b["csv"])
    rq2_dir = os.path.dirname(out_b["csv"])
    for name in ("all_project_corr_hist.pdf", "session_coverage_boxplot.pdf",
                 "average_median_lineplot.pdf",
                 "session_coverage_distribution_trend.pdf"):
        assert os.path.exists(os.path.join(rq2_dir, name)), name


def test_rq2_artifacts_identical_across_backends(study_db, tmp_path):
    paths = {}
    for backend in ("pandas", "jax_tpu", "auto"):
        cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                     limit_date=LIMIT, backend=backend,
                     result_dir=str(tmp_path / ("r_" + backend)))
        paths[backend] = run_rq2_changepoints(cfg, db=study_db)["merged_csv"]
    from pathlib import Path

    contents = {k: Path(v).read_text() for k, v in paths.items()}
    assert contents["pandas"] == contents["jax_tpu"] == contents["auto"]
