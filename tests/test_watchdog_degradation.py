"""Watchdog supervision + degradation ladder + store self-healing
(ISSUE 5 tentpole).

The acceptance property is chaos parity: for each injected failure class
— stall, OOM, device loss, corrupt shard — the degraded/resumed run's
cluster labels equal the uninterrupted run's ELEMENTWISE, every recovery
is recorded as a degradation event, and a corrupt store never returns
wrong labels (the quarantined fraction recomputes).  All injections go
through production fault seats; zero test-only branches in the code
under test.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from tse1m_tpu.cluster import ClusterParams, cluster_sessions
from tse1m_tpu.cluster.store import SignatureStore, file_crc, row_digests
from tse1m_tpu.data.synth import synth_session_sets
from tse1m_tpu.observability import (degradation_counts,
                                     pop_degradation_events,
                                     record_degradation)
from tse1m_tpu.resilience import (FaultPlan, FaultRule, StageWatchdog,
                                  StallError, clear_plan, deadline_guard,
                                  is_device_loss, is_resource_exhausted,
                                  run_with_deadline)

POLICY = {"n_hashes": 32, "seed": 0, "quant_bits": 0}


@pytest.fixture(autouse=True)
def _clean_world():
    clear_plan()
    pop_degradation_events()
    yield
    clear_plan()
    pop_degradation_events()


def _params(store_dir=None, **kw):
    base = dict(n_hashes=32, n_bands=4, use_pallas="never",
                sig_store=str(store_dir) if store_dir else None)
    base.update(kw)
    return ClusterParams(**base)


# -- watchdog unit behavior ---------------------------------------------------

def test_run_with_deadline_cancels_stalled_attempt():
    t0 = time.perf_counter()
    with pytest.raises(StallError):
        run_with_deadline(lambda: time.sleep(5.0), 0.1, "unit")
    assert time.perf_counter() - t0 < 2.0  # cancelled, not waited out


def test_run_with_deadline_relays_results_and_exceptions():
    assert run_with_deadline(lambda: 41 + 1, 5.0, "unit") == 42

    def boom():
        raise ValueError("inner")

    with pytest.raises(ValueError, match="inner"):
        run_with_deadline(boom, 5.0, "unit")
    # budget <= 0 means unguarded (direct call)
    assert run_with_deadline(lambda: "direct", 0.0, "unit") == "direct"


def test_guarded_call_retries_stall_then_succeeds():
    wd = StageWatchdog(min_budget_s=0.15, max_stalls=2)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(2.0)  # first attempt hangs past the budget
        return "ok"

    assert wd.guarded_call("h2d", flaky, site="unit") == "ok"
    events = pop_degradation_events()
    assert [e["kind"] for e in events] == ["stall_retry"]
    assert events[0]["site"] == "unit"


def test_guarded_call_bounded_stalls_then_raises():
    wd = StageWatchdog(min_budget_s=0.1, max_stalls=1)
    with pytest.raises(StallError):
        wd.guarded_call("h2d", lambda: time.sleep(2.0), site="unit")
    kinds = [e["kind"] for e in pop_degradation_events()]
    assert kinds == ["stall_retry", "stall_retry"]  # max_stalls + 1 attempts


def test_budget_adapts_to_observed_rate():
    wd = StageWatchdog(min_budget_s=1.0, factor=2.0, max_stalls=1)
    assert wd.budget_for("h2d", 10**9) == 1.0  # no rate yet: the floor
    wd.observe("h2d", seconds=1.0, nbytes=10 * 2**20)  # 10 MiB/s measured
    b = wd.budget_for("h2d", 100 * 2**20)  # 100 MiB at 10 MiB/s = 10 s
    assert b == pytest.approx(2.0 * 10.0, rel=0.01)
    assert wd.budget_for("h2d", 1) == 1.0  # tiny payload: floor wins
    # stages without a byte dimension use the absolute floor
    assert wd.budget_for("compute") == 1.0


def test_watchdog_seed_rates_bound_first_call():
    wd = StageWatchdog(min_budget_s=1.0, factor=2.0,
                       seed_rates={"h2d": 10e6})  # persisted link probe
    assert wd.budget_for("h2d", 100_000_000) == pytest.approx(20.0)


def test_watchdog_env_kill_switch(monkeypatch):
    monkeypatch.setenv("TSE1M_WATCHDOG", "0")
    wd = StageWatchdog(min_budget_s=0.05, max_stalls=0)
    # Disabled: direct call, no deadline, no events.
    assert wd.guarded_call("h2d", lambda: "ok") == "ok"
    assert wd.budget_for("h2d", 10**12) == 0.0
    assert pop_degradation_events() == []


def test_deadline_guard_fires_only_while_running():
    fired = []
    with pytest.raises(ZeroDivisionError):
        with deadline_guard(0.05, lambda: fired.append(1), site="unit"):
            time.sleep(0.3)  # body outlives the budget -> hook fires
            1 / 0
    assert fired == [1]
    assert [e["kind"] for e in pop_degradation_events()] == [
        "deadline_interrupt"]
    # completion before the budget: the hook must never fire late
    with deadline_guard(0.05, lambda: fired.append(2), site="unit"):
        pass
    time.sleep(0.15)
    assert fired == [1]


def test_failure_classifiers():
    assert is_resource_exhausted(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory allocating 1073741824 bytes"))
    assert not is_resource_exhausted(RuntimeError("unrelated"))
    assert is_device_loss(ConnectionError("any"))
    assert is_device_loss(StallError("site", 1.0))
    assert is_device_loss(RuntimeError("INTERNAL: stream closed: device "
                                       "lost"))
    assert not is_device_loss(ValueError("bad shape"))


# -- calibration file (schema + TTL) -----------------------------------------

def test_calibration_schema_gate(tmp_path):
    from tse1m_tpu.utils.calibration import load_calibration

    path = str(tmp_path / "cal.json")
    # v1 flat layout (no schema_version): ignored wholesale
    with open(path, "w") as f:
        json.dump({"cost_per_row": {"rq1:pandas": 2e-8}}, f)
    assert load_calibration(path) == {"cost_per_row": {}, "wire": {}}
    # future schema: ignored, never half-parsed
    with open(path, "w") as f:
        json.dump({"schema_version": 99, "cost_per_row": {
            "rq1:pandas": {"value": 2e-8, "ts": time.time()}}}, f)
    assert load_calibration(path)["cost_per_row"] == {}
    # unreadable: empty, no raise
    with open(path, "w") as f:
        f.write("{ not json")
    assert load_calibration(path)["wire"] == {}


def test_calibration_ttl_drops_stale_entries(tmp_path, monkeypatch):
    from tse1m_tpu.utils.calibration import (SCHEMA_VERSION,
                                             load_calibration)

    monkeypatch.setenv("TSE1M_ROUTER_CAL_TTL_S", "3600")
    path = str(tmp_path / "cal.json")
    now = time.time()
    with open(path, "w") as f:
        json.dump({"schema_version": SCHEMA_VERSION,
                   "wire": {"h2d_MBps": {"value": 11.0, "ts": now - 7200},
                            "chunk_bytes": {"value": 4096, "ts": now}},
                   "cost_per_row": {
                       "rq1:pandas": {"value": 2e-8, "ts": now - 7200}}},
                  f)
    cal = load_calibration(path)
    # the midnight link measurement must not route the afternoon
    assert cal["wire"] == {"chunk_bytes": 4096}
    assert cal["cost_per_row"] == {}


def test_calibration_update_preserves_prior_timestamps(tmp_path):
    from tse1m_tpu.utils.calibration import update_calibration

    path = str(tmp_path / "cal.json")
    update_calibration(path, wire={"h2d_MBps": 11.0})
    with open(path) as f:
        ts_first = json.load(f)["wire"]["h2d_MBps"]["ts"]
    time.sleep(0.05)
    update_calibration(path, wire={"chunk_bytes": 4096})
    with open(path) as f:
        saved = json.load(f)
    # untouched entry keeps its original stamp (re-stamping would defeat
    # the TTL); the new entry gets a fresh one
    assert saved["wire"]["h2d_MBps"]["ts"] == ts_first
    assert saved["wire"]["chunk_bytes"]["ts"] > ts_first


# -- the degradation ladder (production seats, in-process) -------------------

def test_oom_halves_chunk_and_persists_calibration(tmp_path, monkeypatch):
    """Injected RESOURCE_EXHAUSTED mid-stream: the ladder halves the chunk
    step, resumes without losing completed shards, labels match the
    uninterrupted run elementwise, and the surviving size is persisted so
    the NEXT run's stream plan starts below the observed ceiling."""
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    items = synth_session_sets(2048, set_size=16, seed=3)[0]
    # wire_quant_bits=-1 disables the quant-drop rung (tested in
    # tests/test_quant_rung.py) so this test exercises halving in
    # isolation — halving is the label-invariant rung.
    params = _params(h2d_chunks=4, wire_quant_bits=-1)
    want = cluster_sessions(items, params)
    pop_degradation_events()

    cal = str(tmp_path / "cal.json")
    monkeypatch.setenv("TSE1M_ROUTER_CAL", cal)
    plan = FaultPlan([FaultRule(
        site="pipeline.h2d", kind="raise", after_calls=1, times=1,
        message="RESOURCE_EXHAUSTED: injected 1GiB allocation failure")])
    with plan.active():
        got = cluster_sessions(items, params)
    assert len(plan.fired) == 1
    np.testing.assert_array_equal(got, want)

    counts = degradation_counts(pop_degradation_events())
    assert counts.get("chunk_halving", 0) >= 1
    from tse1m_tpu.cluster.pipeline import _stream_plan, last_run_info

    assert last_run_info["chunk_halvings"] >= 1
    # persisted: the next plan starts at (or below) the surviving size
    with open(cal) as f:
        cal_bytes = json.load(f)["wire"]["chunk_bytes"]["value"]
    row_bytes = items.shape[1] * items.itemsize
    next_step = _stream_plan(items, params)
    assert next_step * row_bytes <= cal_bytes
    monkeypatch.setenv("TSE1M_ROUTER_CAL", "")
    assert _stream_plan(items, params) > next_step  # the clamp was the file


def test_oom_on_smallest_chunk_surfaces(monkeypatch):
    """Out of rungs (step already at the floor): the failure surfaces
    instead of looping forever."""
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    from tse1m_tpu.resilience import InjectedFault

    items = synth_session_sets(64, set_size=16, seed=3)[0]
    plan = FaultPlan([FaultRule(
        site="pipeline.h2d", kind="raise", times=99,
        message="RESOURCE_EXHAUSTED: injected")])  # fires every attempt
    with plan.active():
        with pytest.raises(InjectedFault):
            cluster_sessions(items, _params())


def test_stall_is_cancelled_and_retried(monkeypatch):
    """Injected stall mid-h2d (the failure that never raises): the
    watchdog cancels the attempt past its budget and the retry matches
    the uninterrupted labels."""
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    items = synth_session_sets(1024, set_size=16, seed=5)[0]
    params = _params(h2d_chunks=2)
    want = cluster_sessions(items, params)
    pop_degradation_events()

    monkeypatch.setenv("TSE1M_WATCHDOG_MIN_BUDGET_S", "0.3")
    plan = FaultPlan([FaultRule(site="pipeline.h2d", kind="stall",
                                stall_s=2.5, times=1)])
    t0 = time.perf_counter()
    with plan.active():
        got = cluster_sessions(items, params)
    np.testing.assert_array_equal(got, want)
    assert len(plan.fired) == 1
    counts = degradation_counts(pop_degradation_events())
    assert counts.get("stall_retry", 0) >= 1


def test_device_loss_fails_over_and_completes(monkeypatch):
    """Repeated device-loss-class failures mid-stream: the supervisor
    retries, then fails over for the remainder of the run — labels still
    match the uninterrupted run."""
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    items = synth_session_sets(1024, set_size=16, seed=7)[0]
    params = _params(h2d_chunks=2)
    want = cluster_sessions(items, params)
    pop_degradation_events()

    plan = FaultPlan([FaultRule(site="pipeline.h2d", kind="raise",
                                message="injected: device lost", times=2)])
    with plan.active():
        got = cluster_sessions(items, params)
    np.testing.assert_array_equal(got, want)
    counts = degradation_counts(pop_degradation_events())
    assert counts.get("device_retry", 0) >= 2
    assert counts.get("device_failover", 0) == 1


def test_resumable_path_survives_oom_with_stable_layout(tmp_path,
                                                        monkeypatch):
    """OOM under the checkpointed path: the halved sub-chunks concatenate
    into the SAME shard, so the manifest layout never changes and labels
    match the uninterrupted run."""
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    from tse1m_tpu.cluster import cluster_sessions_resumable

    items = synth_session_sets(2048, set_size=16, seed=11)[0]
    params = _params(h2d_chunks=4)
    want = cluster_sessions(items, params)
    plan = FaultPlan([FaultRule(
        site="pipeline.h2d", kind="raise", after_calls=1, times=1,
        message="RESOURCE_EXHAUSTED: injected")])
    ck = str(tmp_path / "ck")
    with plan.active():
        got = cluster_sessions_resumable(items, params, checkpoint_dir=ck)
    assert len(plan.fired) == 1
    np.testing.assert_array_equal(got, want)


# -- store self-healing: CRC frames, quarantine, scrub -----------------------

def _flip_byte(path: str, offset: int = -1) -> None:
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x40]))


@pytest.mark.parametrize("victim", ["sig", "key"])
def test_bitflip_in_committed_shard_quarantines_and_recomputes(
        tmp_path, victim, monkeypatch):
    """A flipped byte ANYWHERE in a committed sig/key shard is detected
    on load (CRC frame), the shard is quarantined, and the warm run
    recomputes those rows — labels never diverge from cold."""
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    store_dir = tmp_path / "store"
    items = synth_session_sets(1024, set_size=16, seed=13)[0]
    cold = cluster_sessions(items, _params())
    cluster_sessions(items, _params(store_dir))  # populate
    shard_file = str(store_dir / f"{victim}_00000.npy")
    _flip_byte(shard_file, offset=300)  # inside the array data
    pop_degradation_events()

    warm = cluster_sessions(items, _params(store_dir))
    np.testing.assert_array_equal(warm, cold)
    counts = degradation_counts(pop_degradation_events())
    assert counts.get("shard_quarantine", 0) >= 1
    # the evidence moved to quarantine/, and a fresh shard was rebuilt
    qdir = store_dir / "quarantine"
    assert qdir.is_dir() and len(list(qdir.iterdir())) >= 1
    store = SignatureStore(str(store_dir), POLICY)
    hit, _, _ = store.bulk_probe(row_digests(items))
    assert hit.all()  # the warm run re-appended the recomputed rows


def test_corrupt_state_npz_degrades_to_union_not_wrong_labels(tmp_path,
                                                              monkeypatch):
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    store_dir = tmp_path / "store"
    items = synth_session_sets(1024, set_size=16, seed=17)[0]
    cold = cluster_sessions(items, _params())
    cluster_sessions(items, _params(store_dir))  # populate + commit state
    state_files = list(store_dir.glob("state_*.npz"))
    assert state_files
    _flip_byte(str(state_files[0]), offset=100)
    pop_degradation_events()

    from tse1m_tpu.cluster.pipeline import last_run_info

    warm = cluster_sessions(items, _params(store_dir))
    np.testing.assert_array_equal(warm, cold)
    assert last_run_info["cache_mode"] == "union"  # merge shortcut dropped
    counts = degradation_counts(pop_degradation_events())
    assert counts.get("state_quarantine", 0) == 1


def test_scrub_reports_corruption_and_cli_scrub(tmp_path, monkeypatch,
                                                capsys):
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    store_dir = tmp_path / "store"
    items = synth_session_sets(512, set_size=16, seed=19)[0]
    cluster_sessions(items, _params(store_dir))
    _flip_byte(str(store_dir / "sig_00000.npy"), offset=200)

    from tse1m_tpu.cli import main as cli_main

    monkeypatch.setenv("TSE1M_RESULT_DIR", str(tmp_path / "results"))
    rc = cli_main(["scrub", str(store_dir)])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["store_scrub_corrupt"] >= 1
    assert out["store_scrub_quarantined"] >= 1
    assert out["store_scrub_dir"] == str(store_dir)
    # the scrub step landed in the run manifest, events attached
    with open(tmp_path / "results" / "run_manifest.json") as f:
        manifest = json.load(f)
    step = manifest["steps"][0]
    assert step["name"] == "scrub" and step["status"] == "ok"
    assert manifest["degradation_counts"].get("shard_quarantine", 0) >= 1
    # --strict exits nonzero when corruption was found this walk
    # (repopulate first: the corrupt shard above is already quarantined)
    cluster_sessions(items, _params(store_dir))
    _flip_byte(str(store_dir / "key_00000.npy"), offset=200)
    assert cli_main(["scrub", str(store_dir), "--strict"]) == 1
    capsys.readouterr()


def test_scrub_repair_frames_legacy_shards(tmp_path):
    """A pre-CRC store (manifest entries without frames) scrubs clean and
    ``--repair`` adds the missing frames."""
    store_dir = str(tmp_path / "store")
    store = SignatureStore(store_dir, POLICY)
    rng = np.random.default_rng(0)
    items = rng.integers(0, 1 << 20, size=(64, 16), dtype=np.uint32)
    sigs = rng.integers(0, 1 << 31, size=(64, 32), dtype=np.uint32)
    store.append(row_digests(items), sigs)
    # simulate a legacy manifest: strip the frames
    for e in store.shards:
        e.pop("sig_crc", None)
        e.pop("key_crc", None)
    store._write_manifest()

    legacy = SignatureStore.open_existing(store_dir)
    report = legacy.scrub(repair=False)
    assert report["store_scrub_missing_crc"] == 1
    assert report["store_scrub_corrupt"] == 0
    report = legacy.scrub(repair=True)
    assert report["store_scrub_missing_crc"] == 0
    # the repaired frame verifies (and detects a subsequent flip)
    repaired = SignatureStore.open_existing(store_dir)
    assert repaired.quarantined_at_open == []
    _flip_byte(os.path.join(store_dir, "sig_00000.npy"), offset=200)
    flipped = SignatureStore.open_existing(store_dir)
    assert len(flipped.quarantined_at_open) == 1


def test_orphan_sweep_runs_on_open(tmp_path):
    """A crashed compaction/append must not strand temp shards across
    runs: opening the store sweeps everything the manifest doesn't own."""
    store_dir = str(tmp_path / "store")
    store = SignatureStore(store_dir, POLICY)
    rng = np.random.default_rng(1)
    items = rng.integers(0, 1 << 20, size=(32, 16), dtype=np.uint32)
    store.append(row_digests(items),
                 rng.integers(0, 1 << 31, size=(32, 32), dtype=np.uint32))
    strays = ["sig_09999.npy", "key_09999.npy", "sig_00007.npy.tmp.npy",
              "state_00009.npz", "index_deadbeef.keys.npy"]
    for name in strays:
        with open(os.path.join(store_dir, name), "wb") as f:
            f.write(b"\x93NUMPY garbage")
    reopened = SignatureStore(store_dir, POLICY)
    for name in strays:
        assert not os.path.exists(os.path.join(store_dir, name)), name
    assert reopened.n_rows == 32  # committed data untouched


def test_compaction_folds_shards_and_preserves_warm_merge(tmp_path,
                                                          monkeypatch):
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    store_dir = tmp_path / "store"
    base = synth_session_sets(768, set_size=16, seed=23)[0]
    tail = synth_session_sets(96, set_size=16, seed=29)[0]
    grown = np.concatenate([base, tail])
    cold = cluster_sessions(grown, _params())
    cluster_sessions(base, _params(store_dir))   # shard 0 + state
    cluster_sessions(grown, _params(store_dir))  # appends shard 1, merge

    store = SignatureStore.open_existing(str(store_dir))
    assert len(store.shards) >= 2
    folded = store.compact()
    assert folded >= 2 and len(store.shards) == 1
    # the remapped state still drives an exact merge (not a rebuild)
    from tse1m_tpu.cluster.pipeline import last_run_info

    warm = cluster_sessions(grown, _params(store_dir))
    np.testing.assert_array_equal(warm, cold)
    assert last_run_info["cache_mode"] == "merge"
    assert last_run_info["cache_hit_rate"] == pytest.approx(1.0)


def test_auto_compaction_at_open_past_threshold(tmp_path, monkeypatch):
    store_dir = str(tmp_path / "store")
    rng = np.random.default_rng(2)
    store = SignatureStore(store_dir, POLICY)
    for _ in range(4):
        items = rng.integers(0, 1 << 20, size=(16, 16), dtype=np.uint32)
        store.append(row_digests(items),
                     rng.integers(0, 1 << 31, size=(16, 32),
                                  dtype=np.uint32))
    assert len(store.shards) == 4
    monkeypatch.setenv("TSE1M_SIG_STORE_COMPACT_SHARDS", "3")
    reopened = SignatureStore(store_dir, POLICY)
    assert len(reopened.shards) == 1
    assert reopened.n_rows == store.n_rows


def test_eviction_is_lru_by_probe_recency(tmp_path):
    """Under max_bytes pressure the shard with the OLDEST probe
    generation goes first — not the oldest shard id (FIFO would evict
    the hottest data in a probe-skewed workload)."""
    store_dir = str(tmp_path / "store")
    rng = np.random.default_rng(3)
    store = SignatureStore(store_dir, POLICY)
    batches = []
    for _ in range(3):
        items = rng.integers(0, 1 << 20, size=(32, 16), dtype=np.uint32)
        batches.append(items)
        store.append(row_digests(items),
                     rng.integers(0, 1 << 31, size=(32, 32),
                                  dtype=np.uint32))
    # shard 0 is the OLDEST but the only one recently probed
    store.bulk_probe(row_digests(batches[0]))
    # cap to ~2 shards' worth of signature bytes; the next append evicts
    shard_bytes = 32 * 32 * 4
    store.max_bytes = int(2.5 * shard_bytes)
    items = rng.integers(0, 1 << 20, size=(32, 16), dtype=np.uint32)
    store.append(row_digests(items),
                 rng.integers(0, 1 << 31, size=(32, 32), dtype=np.uint32))
    kept = store.shard_ids()
    assert 0 in kept        # recently probed: survives
    assert 1 not in kept    # coldest probe_gen: evicted first
    hit, _, _ = store.bulk_probe(row_digests(batches[0]))
    assert hit.all()
    hit, _, _ = store.bulk_probe(row_digests(batches[1]))
    assert not hit.any()    # evicted rows probe as misses (recompute)


def test_checkpoint_shard_bitflip_reads_as_not_done(tmp_path):
    from tse1m_tpu.cluster.checkpoint import ClusterCheckpoint

    class P:
        n_hashes, n_bands, seed = 32, 4, 0

    items = np.arange(64 * 16, dtype=np.uint32).reshape(64, 16)
    ck = ClusterCheckpoint(str(tmp_path / "ck"), items, P, step=32)
    sig = np.ones((32, 32), np.uint32)
    keys = np.ones((32, 4), np.uint32)
    ck.save_chunk(0, sig, keys)
    assert ck.chunk_done(0)
    _flip_byte(ck._shard_path(0), offset=200)
    assert not ck.chunk_done(0)  # CRC frame catches bit rot, not just torn
    # a resume sees it as pending and recomputes
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), items, P, step=32)
    assert not ck2.chunk_done(0)


# -- bounded digest-index memory (mmap probe mode) ---------------------------

def test_mmap_index_mode_probes_and_verifies(tmp_path, monkeypatch):
    store_dir = str(tmp_path / "store")
    rng = np.random.default_rng(5)
    store = SignatureStore(store_dir, POLICY)
    items = rng.integers(0, 1 << 24, size=(4096, 16), dtype=np.uint32)
    sigs = rng.integers(0, 1 << 31, size=(4096, 32), dtype=np.uint32)
    store.append(row_digests(items), sigs)

    monkeypatch.setenv("TSE1M_SIG_STORE_IDX_ROWS", "64")
    mm = SignatureStore(store_dir, POLICY)
    assert mm._idx_mode == "mmap"
    digests = row_digests(items)
    hit, shard, row = mm.bulk_probe(digests)
    assert hit.all()
    got = mm.load_signatures(shard, row)
    np.testing.assert_array_equal(got, sigs)
    # misses stay misses
    other = rng.integers(1 << 24, 1 << 28, size=(64, 16), dtype=np.uint32)
    hit, _, _ = mm.bulk_probe(row_digests(other))
    assert not hit.any()
    # a rotted index locator downgrades to a miss, never a wrong gather:
    # corrupt the index loc file in place and re-open (index fingerprint
    # unchanged, so the poisoned file is reused)
    loc_path = mm._index_paths()[1]
    loc = np.load(loc_path)
    loc[:, 1] = (loc[:, 1] + 1) % 4096  # every locator points elsewhere
    np.save(loc_path, loc)
    poisoned = SignatureStore(store_dir, POLICY)
    assert poisoned._idx_mode == "mmap"
    hit, shard, row = poisoned.bulk_probe(digests[:100])
    assert not hit.any()  # verification caught every bad locator


def test_mmap_index_bounds_probe_rss(tmp_path):
    """The satellite's RSS pin: past the row threshold, opening a store
    must NOT materialize the digest index in RAM — the in-RAM mode pays
    keys + locators (+ sort temporaries) up front, the mmap mode maps
    files and pays only the pages a probe touches.  (The probe itself is
    measured with generous slack: on THP-backed filesystems a single
    touched page can fault a 2 MB huge page.)"""
    import subprocess
    import sys

    store_dir = str(tmp_path / "store")
    policy = {"n_hashes": 8, "seed": 0, "quant_bits": 0}
    rng = np.random.default_rng(6)
    store = SignatureStore(store_dir, policy)
    n = 1_200_000
    items = rng.integers(0, 1 << 30, size=(n, 4), dtype=np.uint32)
    sigs = rng.integers(0, 1 << 31, size=(n, 8), dtype=np.uint32)
    store.append(row_digests(items), sigs)
    index_kb = (n * 16 + n * 8) // 1024  # keys2d + locators
    # pre-build the mmap index files so the child pays open cost only
    os.environ["TSE1M_SIG_STORE_IDX_ROWS"] = "1000"
    try:
        SignatureStore(store_dir, policy)
    finally:
        os.environ.pop("TSE1M_SIG_STORE_IDX_ROWS")
    probe_rows = items[rng.choice(n, size=100, replace=False)]
    np.save(os.path.join(store_dir, "probe.npy"), probe_rows)

    # Anonymous-RSS deltas (RssAnon): file-backed mmap pages are clean,
    # evictable page cache the kernel reclaims under pressure — the
    # bounded-memory claim is about process-owned HEAP.  The in-RAM index
    # holds keys + locators as anonymous memory forever; the mmap mode's
    # anonymous footprint is just the probe's own temporaries.  (Plain
    # RSS would also be blind to the import peak and THP fault rounding.)
    child = (
        "import json, os, sys\n"
        "import numpy as np\n"
        "from tse1m_tpu.cluster.store import SignatureStore, row_digests\n"
        "def anon_kb():\n"
        "    with open('/proc/self/status') as f:\n"
        "        for line in f:\n"
        "            if line.startswith('RssAnon:'):\n"
        "                return int(line.split()[1])\n"
        "    raise RuntimeError('no RssAnon')\n"
        "d = sys.argv[1]\n"
        "q = row_digests(np.load(os.path.join(d, 'probe.npy')))\n"
        "base = anon_kb()\n"
        "s = SignatureStore(d, {'n_hashes': 8, 'seed': 0, 'quant_bits': 0})\n"
        "opened = anon_kb()\n"
        "hit, _, _ = s.bulk_probe(q)\n"
        "assert hit.all()\n"
        "print(json.dumps({'mode': s._idx_mode,\n"
        "                  'open_kb': int(opened - base),\n"
        "                  'probe_kb': int(anon_kb() - opened)}))\n")

    def run(idx_rows: str) -> dict:
        env = dict(os.environ, TSE1M_SIG_STORE_IDX_ROWS=idx_rows,
                   JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.run([sys.executable, "-c", child, store_dir],
                              env=env, capture_output=True, text=True,
                              timeout=300, cwd="/root/repo")
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    ram = run(str(10**9))
    mm = run("1000")
    assert ram["mode"] == "ram" and mm["mode"] == "mmap"
    # RAM open materializes the full index (~28 MB here) as anonymous
    # heap; mmap open maps files and owns (almost) nothing.
    assert ram["open_kb"] > index_kb * 0.8, (ram, index_kb)
    assert mm["open_kb"] < index_kb * 0.3, (mm, index_kb)
    # and the whole mmap open+probe keeps anonymous growth bounded by the
    # query's own temporaries, far under materialization
    assert mm["open_kb"] + mm["probe_kb"] < index_kb * 0.5, (mm, index_kb)


# -- manifest/observability wiring -------------------------------------------

def test_step_runner_embeds_degradation_events(tmp_path):
    from tse1m_tpu.resilience import StepRunner

    path = str(tmp_path / "m.json")
    runner = StepRunner(path)

    def degraded_step():
        record_degradation("chunk_halving", site="test",
                           detail={"to_rows": 64})
        return {"ok": True}

    runner.run("work", degraded_step)
    runner.run("clean", lambda: None)
    with open(path) as f:
        manifest = json.load(f)
    work, clean = manifest["steps"]
    assert [e["kind"] for e in work["degradations"]] == ["chunk_halving"]
    assert clean["degradations"] is None  # isolation between steps
    assert manifest["degradation_counts"] == {"chunk_halving": 1}


def test_cluster_cli_reports_degradation_keys(tmp_path, monkeypatch,
                                              capsys):
    monkeypatch.delenv("TSE1M_ROUTER_CAL", raising=False)
    from tse1m_tpu.cli import main as cli_main

    monkeypatch.setenv("TSE1M_RESULT_DIR", str(tmp_path / "results"))
    rc = cli_main(["cluster", "--n", "512", "--ari-sample", "0"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["chunk_halvings"] == 0  # present even on a clean run
    assert "degradation_events" in out


# -- graftlint: watchdog-clock -----------------------------------------------

def test_watchdog_clock_rule(tmp_path):
    from tse1m_tpu.lint import engine as lint_engine
    from tse1m_tpu.lint.rules import RULES

    bad = tmp_path / "bad.py"
    bad.write_text("import time\n"
                   "def arm_watchdog(budget):\n"
                   "    t0 = time.monotonic()\n"
                   "    return t0 + budget\n"
                   "def stall_check():\n"
                   "    return time.perf_counter()\n"
                   "def unrelated_telemetry():\n"
                   "    return time.time()\n")
    src = lint_engine.load_source(str(bad),
                                  "tse1m_tpu/cluster/pipeline.py")
    findings = RULES["watchdog-clock"](src)
    # the two deadline-named functions fire; the unrelated one does not
    assert len(findings) == 2
    # inside the plane module, EVERY raw clock call fires except the
    # helper itself
    plane = tmp_path / "plane.py"
    plane.write_text("import time\n"
                     "def deadline_clock():\n"
                     "    return time.monotonic()\n"
                     "def helper():\n"
                     "    return time.monotonic()\n")
    src = lint_engine.load_source(str(plane),
                                  "tse1m_tpu/resilience/watchdog.py")
    findings = RULES["watchdog-clock"](src)
    assert len(findings) == 1 and findings[0].line == 5


def test_fault_plan_stall_kind_sleeps_through(monkeypatch):
    from tse1m_tpu.resilience import fault_point

    plan = FaultPlan([FaultRule(site="unit.stall", kind="stall",
                                stall_s=0.2, times=1)])
    with plan.active():
        t0 = time.perf_counter()
        fault_point("unit.stall")  # stalls, then passes through
        elapsed = time.perf_counter() - t0
    assert elapsed >= 0.2
    assert len(plan.fired) == 1
