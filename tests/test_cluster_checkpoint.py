"""Checkpointed cluster pipeline (cluster/checkpoint.py +
cluster_sessions_resumable) — SURVEY §5 A4's device-side seat: per-chunk
signature shards with kill-and-resume, the TPU analogue of the reference's
batch-file checkpointing (2_get_buildlog_metadata.py:141-147).
"""

from __future__ import annotations

import numpy as np
import pytest

import tse1m_tpu.cluster.pipeline as pipeline_mod
from tse1m_tpu.cluster import (ClusterParams, cluster_sessions,
                               cluster_sessions_resumable)
from tse1m_tpu.cluster.checkpoint import ClusterCheckpoint
from tse1m_tpu.data.synth import synth_session_sets

# 2048 rows at block_n=512 and 4 chunks -> 4 shards of 512 rows.
PARAMS = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never",
                       h2d_chunks=4)
N = 2048


@pytest.fixture(scope="module")
def items():
    return synth_session_sets(N, set_size=16, seed=13)[0]


def test_resumable_matches_plain(items, tmp_path):
    want = cluster_sessions(items, PARAMS)
    got = cluster_sessions_resumable(items, PARAMS,
                                     checkpoint_dir=str(tmp_path / "ck"))
    np.testing.assert_array_equal(got, want)


def test_cleanup_after_success(items, tmp_path):
    d = tmp_path / "ck"
    cluster_sessions_resumable(items, PARAMS, checkpoint_dir=str(d))
    assert not list(d.glob("shard_*.npz"))
    assert not (d / "manifest.json").exists()


def test_kill_and_resume_recomputes_only_missing_chunks(items, tmp_path,
                                                        monkeypatch):
    d = str(tmp_path / "ck")
    want = cluster_sessions(items, PARAMS)

    class Boom(RuntimeError):
        pass

    # "Kill" the run after two chunks have been durably saved.
    saved = []
    real_save = ClusterCheckpoint.save_chunk

    def dying_save(self, index, sig, keys):
        real_save(self, index, sig, keys)
        saved.append(index)
        if len(saved) == 2:
            raise Boom()

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", dying_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(items, PARAMS, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)

    # Resume: only the remaining chunks may hit the compute path
    # (_chunk_minhash is the per-chunk decode+MinHash seat).
    computed = []
    real_mk = pipeline_mod._chunk_minhash

    def counting_mk(*a, **kw):
        computed.append(1)
        return real_mk(*a, **kw)

    monkeypatch.setattr(pipeline_mod, "_chunk_minhash", counting_mk)
    got = cluster_sessions_resumable(items, PARAMS, checkpoint_dir=d)
    n_chunks = -(-N // 512)
    assert len(computed) == n_chunks - 2
    np.testing.assert_array_equal(got, want)


def test_crash_mid_write_recomputes_that_chunk(items, tmp_path, monkeypatch):
    """A torn shard write (crash between file write and manifest update)
    must leave the chunk 'not done'."""
    d = str(tmp_path / "ck")

    class Boom(RuntimeError):
        pass

    real_save = ClusterCheckpoint.save_chunk

    def torn_save(self, index, sig, keys):
        if index == 1:
            # shard file lands, manifest never updates
            np.savez(self._shard_path(index) + ".tmp.npz", sig=sig,
                     keys=keys)
            raise Boom()
        real_save(self, index, sig, keys)

    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", torn_save)
    with pytest.raises(Boom):
        cluster_sessions_resumable(items, PARAMS, checkpoint_dir=d)
    monkeypatch.setattr(ClusterCheckpoint, "save_chunk", real_save)
    ck = ClusterCheckpoint(d, items, PARAMS, 512)
    assert not ck.chunk_done(1)
    assert ck.chunk_done(0)
    got = cluster_sessions_resumable(items, PARAMS, checkpoint_dir=d)
    np.testing.assert_array_equal(got, cluster_sessions(items, PARAMS))
    # cleanup after the successful resume also swept the orphaned tmp file
    import glob
    import os

    assert not glob.glob(os.path.join(d, "shard_*"))


def test_refuses_mismatched_checkpoint(items, tmp_path):
    d = str(tmp_path / "ck")
    ClusterCheckpoint(d, items, PARAMS, 512)
    other = ClusterParams(n_hashes=64, n_bands=4, use_pallas="never")
    with pytest.raises(ValueError, match="different"):
        ClusterCheckpoint(d, items, other, 512)
    # different items too
    items2 = synth_session_sets(N, set_size=16, seed=99)[0]
    with pytest.raises(ValueError, match="different"):
        ClusterCheckpoint(d, items2, PARAMS, 512)
