"""Shared harness for the 2-process pod runs: the slow chaos test
(tests/test_pod_chaos.py) and the CI fault-matrix ``hostloss`` /
``heartbeat-timeout`` seats (tests/ci_fault_matrix.py) spawn the same
production driver (tests/chaos_drivers.py ``pod``) through here.

Every run is real: two worker processes bring up `jax.distributed` over
a local coordinator, shard the signature store by digest range, beat
heartbeats, exchange novel tails over the shared store root (the pod
data plane — no cross-process XLA executable, which the CPU backend
cannot run at all), and either finish together or lose a worker to an
injected fault and fail over."""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "chaos_drivers.py")

# Pod chaos timing: fast beats so a loss is declared in seconds, with
# enough slack that single-core CI noise (compiles hold the box busy)
# cannot fake one.
POD_ENV = {
    "JAX_PLATFORMS": "cpu",
    "TSE1M_HEARTBEAT_INTERVAL_S": "0.2",
    "TSE1M_HEARTBEAT_TIMEOUT_S": "5",
    "TSE1M_WATCHDOG": "0",  # the pod plane under test, not the stage one
}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(base: dict, port: int, pid: int, nproc: int,
                plan: dict | None, tmp: str) -> dict:
    env = dict(base)
    env.update(POD_ENV)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + (os.pathsep + env["PYTHONPATH"]
                                if env.get("PYTHONPATH") else "")
    env.update({"TSE1M_COORDINATOR": f"127.0.0.1:{port}",
                "TSE1M_NUM_PROCESSES": str(nproc),
                "TSE1M_PROCESS_ID": str(pid)})
    env.pop("TSE1M_FAULT_PLAN", None)
    if plan is not None:
        plan_path = os.path.join(tmp, f"plan_p{pid}.json")
        with open(plan_path, "w") as f:
            json.dump(plan, f)
        env["TSE1M_FAULT_PLAN"] = plan_path
    return env


def spawn_pod(tmp: str, store: str, result_dir: str, n: int = 800,
              seed: int = 13, plans: dict | None = None,
              timeout: int = 480, expect_finish=(0,),
              straggler_timeout: int = 30, on_poll=None) -> dict:
    """Run one 2-process pod clustering; returns per-pid
    {rc, out, err, labels, info}.  ``plans`` maps pid -> fault plan dict
    (installed only in that worker).  ``expect_finish`` names the pids
    that must exit on their own (the survivors — with leader-loss
    promotion that can be pid 1); once they have, any remaining worker
    gets ``straggler_timeout`` seconds and is then SIGKILLed — the
    fencing a real scheduler provides for a forever-wedged host.
    ``on_poll`` (optional callable) runs each poll tick — the zombie
    test uses it to touch the wake file once the survivor's epoch
    advance is on disk."""
    import time as _time

    port = free_port()
    plans = plans or {}
    expect_finish = set(expect_finish)
    procs, outs, infos = [], [], []
    for pid in range(2):
        out = os.path.join(tmp, f"labels_p{pid}.npy")
        info = os.path.join(tmp, f"info_p{pid}.json")
        outs.append(out)
        infos.append(info)
        env = _worker_env(dict(os.environ), port, pid, 2,
                          plans.get(pid), tmp)
        procs.append(subprocess.Popen(
            [sys.executable, DRIVER, "pod", "--store-dir", store,
             "--out", out, "--info", info, "--n", str(n),
             "--seed", str(seed), "--result-dir", result_dir],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))

    def _poll_until(pids, deadline) -> None:
        while _time.monotonic() < deadline:
            if on_poll is not None:
                on_poll()
            if all(procs[p].poll() is not None for p in pids):
                return
            _time.sleep(0.25)

    _poll_until(expect_finish, _time.monotonic() + timeout)
    _poll_until({0, 1}, _time.monotonic() + straggler_timeout)
    results: dict[int, dict] = {}
    for pid in (0, 1):
        p = procs[pid]
        if p.poll() is None:
            p.kill()
        out_s, err_s = p.communicate(timeout=60)
        results[pid] = {"rc": p.returncode, "out": out_s, "err": err_s}
    import numpy as np

    for pid in (0, 1):
        r = results[pid]
        r["labels"] = (np.load(outs[pid])
                       if os.path.exists(outs[pid]) else None)
        r["info"] = (json.load(open(infos[pid]))
                     if os.path.exists(infos[pid]) else None)
    return results


def run_single_pod(tmp: str, store: str, n: int = 800, seed: int = 13,
                   result_dir: str | None = None,
                   timeout: int = 300) -> dict:
    """One single-process pod run (the resumed-after-host-loss shape)."""
    out = os.path.join(tmp, "labels_single.npy")
    info = os.path.join(tmp, "info_single.json")
    env = dict(os.environ)
    env.update(POD_ENV)
    env["PYTHONPATH"] = REPO + (os.pathsep + env["PYTHONPATH"]
                                if env.get("PYTHONPATH") else "")
    for k in ("TSE1M_COORDINATOR", "TSE1M_NUM_PROCESSES",
              "TSE1M_PROCESS_ID", "TSE1M_FAULT_PLAN", "XLA_FLAGS"):
        env.pop(k, None)
    cmd = [sys.executable, DRIVER, "pod", "--store-dir", store,
           "--out", out, "--info", info, "--n", str(n),
           "--seed", str(seed)]
    if result_dir:
        cmd += ["--result-dir", result_dir]
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)
    import numpy as np

    return {"rc": proc.returncode, "out": proc.stdout, "err": proc.stderr,
            "labels": np.load(out) if os.path.exists(out) else None,
            "info": json.load(open(info)) if os.path.exists(info)
            else None}


def cold_labels(tmp: str, n: int = 800, seed: int = 13,
                timeout: int = 300):
    """The uninterrupted-run oracle: a plain storeless single-process
    run of the same deterministic corpus under the same ClusterParams
    the pod driver uses."""
    out = os.path.join(tmp, "labels_cold.npy")
    env = dict(os.environ)
    env.update(POD_ENV)
    env["PYTHONPATH"] = REPO + (os.pathsep + env["PYTHONPATH"]
                                if env.get("PYTHONPATH") else "")
    for k in ("TSE1M_COORDINATOR", "TSE1M_NUM_PROCESSES",
              "TSE1M_PROCESS_ID", "TSE1M_FAULT_PLAN", "XLA_FLAGS"):
        env.pop(k, None)
    ckpt = tempfile.mkdtemp(dir=tmp, prefix="cold_ckpt_")
    proc = subprocess.run(
        [sys.executable, DRIVER, "cluster", "--dir", ckpt, "--out", out,
         "--n", str(n), "--seed", str(seed)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stderr[-2000:]
    import numpy as np

    return np.load(out)


KILL_WORKER_PLAN = {"rules": [{"site": "pipeline.h2d", "kind": "kill"}]}
WEDGE_WORKER_PLAN = {"rules": [{"site": "pipeline.h2d",
                                "kind": "hostloss", "stall_s": 300}]}
SIGKILL = -signal.SIGKILL


def zombie_plan(wake_path: str, stall_s: float = 240.0) -> dict:
    """A wedged-then-woken writer: heartbeats suspend at the first H2D
    put, the process sleeps until ``wake_path`` appears (the parent
    touches it once the survivor's epoch advance is on disk — see
    ``make_zombie_waker``), then heartbeats resume and the writer
    continues straight into its superseded-lease append."""
    return {"rules": [{"site": "pipeline.h2d", "kind": "zombie",
                       "stall_s": stall_s, "wake_path": wake_path}]}


def make_zombie_waker(store: str, wake_path: str):
    """An ``on_poll`` callback: touch ``wake_path`` once the pod's
    membership ledger shows an advanced epoch (>= 1) — i.e. the
    survivor has re-dealt the zombie's range and superseded its lease,
    so waking it now deterministically exercises the fence."""
    membership = os.path.join(store, "pod", "membership.json")

    def _tick() -> None:
        if os.path.exists(wake_path):
            return
        try:
            with open(membership) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return
        if int(rec.get("epoch", 0)) >= 1:
            open(wake_path, "w").close()

    return _tick
