"""Offline test of SeleniumIssueClient against a fake webdriver.

selenium is an optional dependency and is not installed in CI, so the test
injects a miniature stand-in for the handful of selenium modules the
client imports lazily, plus a small DOM tree + CSS/XPath matcher shaped
like the tracker pages (reference selectors 5_get_issue_reports.py:59-290).
Every code path of the client runs for real: happy-path scrape (title,
metadata labels, person fields, events, revision links, description,
hotlists), throttle-detect-and-retry, load failure, and the shadow-DOM
revision table with its failed-page branch.
"""

from __future__ import annotations

import re
import sys
import types

import pytest

# ---------------------------------------------------------------------------
# Fake DOM
# ---------------------------------------------------------------------------


class NoSuchElementException(Exception):
    pass


class TimeoutException(Exception):
    pass


class FakeElement:
    def __init__(self, tag, classes=(), text="", attrs=None, children=(),
                 displayed=True, shadow=None):
        self.tag = tag
        self.classes = set(classes)
        self.own_text = text
        self.attrs = dict(attrs or {})
        self.children = list(children)
        self.displayed = displayed
        self._shadow = shadow

    # -- selenium surface --
    @property
    def text(self):
        parts = [self.own_text] + [c.text for c in self.children]
        return " ".join(p for p in parts if p).strip()

    def get_attribute(self, name):
        return self.attrs.get(name)

    def is_displayed(self):
        return self.displayed

    @property
    def shadow_root(self):
        if self._shadow is None:
            raise NoSuchElementException("no shadow root")
        return self._shadow

    def find_elements(self, by, sel):
        return _find(self, by, sel)

    def find_element(self, by, sel):
        found = _find(self, by, sel)
        if not found:
            raise NoSuchElementException(f"{by}: {sel}")
        return found[0]

    # -- internals --
    def walk(self):
        """(node, ancestors-from-outermost) over the subtree, self excluded."""
        stack = [(c, [self]) for c in self.children]
        while stack:
            node, anc = stack.pop(0)
            yield node, anc
            stack = [(c, anc + [node]) for c in node.children] + stack


_SIMPLE = re.compile(r"^([a-zA-Z][\w-]*)?((?:\.[\w-]+)*)((?:\[[^\]]+\])*)$")
_ATTR = re.compile(r'\[([\w-]+)\*="([^"]+)"\]')


def _match_simple(el, part):
    m = _SIMPLE.match(part)
    if not m:
        raise ValueError(f"unsupported selector: {part!r}")
    tag, classes, attrs = m.groups()
    if tag and el.tag != tag:
        return False
    if not {c for c in classes.split(".") if c} <= el.classes:
        return False
    return all(sub in (el.attrs.get(a) or "")
               for a, sub in _ATTR.findall(attrs))


def _css_select(root, selector):
    out = []
    for alt in selector.split(","):
        parts = alt.strip().split()
        for node, anc in root.walk():
            if not _match_simple(node, parts[-1]):
                continue
            chain, need = list(anc), parts[:-1]
            while need:
                want = need[-1]
                while chain and not _match_simple(chain[-1], want):
                    chain.pop()
                if not chain:
                    break
                chain.pop()
                need.pop()
            if not need and node not in out:
                out.append(node)
    return out


def _find(root, by, sel):
    if by == "css selector":
        return _css_select(root, sel)
    if by == "tag name":
        return [n for n, _ in root.walk() if n.tag == sel]
    if by == "xpath":
        # Only the two contains() probes the client uses.
        if "snackbar-content" in sel:
            return [n for n, _ in root.walk()
                    if "snackbar-content" in n.classes
                    and "Request throttled" in n.text]
        text = re.search(r"contains\(text\(\), '([^']+)'\)", sel)
        if text:
            return [n for n, _ in root.walk() if text.group(1) in n.own_text]
    raise ValueError(f"unsupported locator {by}: {sel}")


class FakeDriver:
    def __init__(self):
        self.routes = {}          # url -> [(final_url, root), ...]
        self.current_url = "about:blank"
        self.root = FakeElement("html")
        self.navigations = []
        self.quit_called = False

    def add_route(self, url, root, final_url=None, once=False):
        self.routes.setdefault(url, []).append(
            (final_url or url, root, once))

    def get(self, url):
        self.navigations.append(url)
        entries = self.routes.get(url)
        if not entries:
            self.current_url = url
            self.root = FakeElement("html")
            return
        final_url, root, once = entries[0]
        if once and len(entries) > 1:
            entries.pop(0)
        self.current_url = final_url
        self.root = root

    def find_element(self, by, sel):
        return self.root.find_element(by, sel)

    def find_elements(self, by, sel):
        return self.root.find_elements(by, sel)

    def quit(self):
        self.quit_called = True


# ---------------------------------------------------------------------------
# Fake selenium package
# ---------------------------------------------------------------------------


@pytest.fixture()
def fake_selenium(monkeypatch):
    class ChromeOptions:
        def __init__(self):
            self.args = []

        def add_argument(self, a):
            self.args.append(a)

    holder = {"driver": None}

    def chrome(options=None):
        assert options is not None and "--headless" in options.args
        return holder["driver"]

    class WebDriverWait:
        def __init__(self, driver, timeout):
            self.driver = driver

        def until(self, cond):
            for _ in range(5):
                try:
                    v = cond(self.driver)
                    if v:
                        return v
                except NoSuchElementException:
                    pass
            raise TimeoutException()

    class By:
        CSS_SELECTOR = "css selector"
        TAG_NAME = "tag name"
        XPATH = "xpath"

    ec = types.ModuleType("selenium.webdriver.support.expected_conditions")
    ec.presence_of_element_located = (
        lambda locator: lambda d: d.find_element(*locator))

    mods = {}

    def mod(name, **attrs):
        m = types.ModuleType(name)
        for k, v in attrs.items():
            setattr(m, k, v)
        mods[name] = m
        return m

    webdriver = mod("selenium.webdriver", ChromeOptions=ChromeOptions,
                    Chrome=chrome)
    mod("selenium", webdriver=webdriver)
    mod("selenium.common")
    mod("selenium.common.exceptions",
        NoSuchElementException=NoSuchElementException,
        TimeoutException=TimeoutException)
    mod("selenium.webdriver.common")
    mod("selenium.webdriver.common.by", By=By)
    support = mod("selenium.webdriver.support", expected_conditions=ec)
    mod("selenium.webdriver.support.ui", WebDriverWait=WebDriverWait)
    mods["selenium.webdriver.support.expected_conditions"] = ec
    support.ui = mods["selenium.webdriver.support.ui"]
    for name, m in mods.items():
        monkeypatch.setitem(sys.modules, name, m)
    monkeypatch.setattr("time.sleep", lambda s: None)
    return holder


# ---------------------------------------------------------------------------
# Page builders
# ---------------------------------------------------------------------------


def meta_field(label, value):
    return FakeElement("b-edit-field", children=[
        FakeElement("label", text=label),
        FakeElement("div", classes={"bv2-metadata-field-value"}, text=value),
    ])


def user_field(label, people):
    return FakeElement("b-multi-user-control", children=[
        FakeElement("label", text=label),
        *[FakeElement("b-person-hovercard", text=p) for p in people],
    ])


def event_div(text, time_iso=None, links=()):
    children = [FakeElement("b-plain-format-unquoted-section", text=text)]
    if time_iso:
        children.append(FakeElement("h4", children=[
            FakeElement("b-formatted-date-time", children=[
                FakeElement("time", attrs={"datetime": time_iso})])]))
    children += [FakeElement("a", attrs={"href": u}) for u in links]
    return FakeElement("div", classes={"bv2-event"}, children=children)


REV_URL = "https://issues.oss-fuzz.com/action/revisions?range=1700:1800"


def loaded_issue_page():
    return FakeElement("html", children=[
        FakeElement("b-issue-details"),
        FakeElement("h3", classes={"heading-m", "ng-star-inserted"},
                    text="zlib: Heap-buffer-overflow in inflate"),
        FakeElement("b-hotlist-chip-smart", children=[
            FakeElement("span", classes={"name"}, children=[
                FakeElement("a", text="OSS-Fuzz")])]),
        FakeElement("b-formatted-date-time", children=[
            FakeElement("time", attrs={"datetime": "2024-04-01T00:00:00Z"})]),
        FakeElement("edit-issue-metadata", children=[
            meta_field("Status", "Fixed"),
            meta_field("Type", "Vulnerability"),
            meta_field("Priority", "--"),
            meta_field("Unknown Label", "dropped"),
            user_field("Reporter", ["ClusterFuzz"]),
            user_field("CC", ["a@chromium.org", "b@chromium.org"]),
            user_field("Assignee", ["--"]),
        ]),
        FakeElement("issue-event-list", children=[
            event_div("ClusterFuzz testcase 123 is verified as fixed in "
                      f"{REV_URL}", time_iso="2024-05-01T10:00:00Z",
                      links=[REV_URL]),
            event_div("unrelated comment"),
        ]),
        FakeElement("b-issue-description",
                    text="Detailed Report: crash in inflate"),
    ])


def throttled_page():
    return FakeElement("html", children=[
        FakeElement("div", classes={"snackbar-content"},
                    text="Request throttled. Please try again later.")])


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------


def make_client(fake_selenium, **kw):
    from tse1m_tpu.collect.issues_selenium import SeleniumIssueClient

    driver = FakeDriver()
    fake_selenium["driver"] = driver
    kw.setdefault("page_delay", (0, 0))
    return SeleniumIssueClient(**kw), driver


def test_fetch_issue_happy_path(fake_selenium):
    from tse1m_tpu.collect.issues import issue_url

    client, driver = make_client(fake_selenium)
    url = issue_url(42_000_000)
    driver.add_route(url, loaded_issue_page(),
                     final_url="https://issues.oss-fuzz.com/issues/42000001")
    page = client.fetch_issue(42_000_000)

    assert not page.load_error
    assert page.final_id == "42000001"          # redirect target id
    assert page.title == "zlib: Heap-buffer-overflow in inflate"
    assert page.hotlists == ["OSS-Fuzz"]
    assert page.reported_time_iso == "2024-04-01T00:00:00Z"
    assert page.metadata == {
        "Status": "Fixed",
        "Type": "Vulnerability",
        "Priority": None,                        # "--" -> None
        "Reporter": "ClusterFuzz",
        "CC": ["a@chromium.org", "b@chromium.org"],
        "Assignee": None,
    }
    assert "Unknown Label" not in page.metadata
    assert len(page.events) == 2
    assert page.events[0].time_iso == "2024-05-01T10:00:00Z"
    assert page.events[0].revision_links == [REV_URL]
    assert page.events[1].revision_links == []
    assert page.description.startswith("Detailed Report")
    client.close()
    assert driver.quit_called


def test_fetch_issue_throttled_then_recovers(fake_selenium):
    from tse1m_tpu.collect.issues import issue_url

    client, driver = make_client(fake_selenium, throttle_wait=0.0,
                                 max_retries=3)
    url = issue_url(42_000_000)
    driver.add_route(url, throttled_page(), once=True)
    driver.add_route(url, loaded_issue_page())
    page = client.fetch_issue(42_000_000)
    assert not page.load_error
    assert driver.navigations.count(url) == 2   # one throttle + one success


def test_fetch_issue_load_failure(fake_selenium):
    client, driver = make_client(fake_selenium, max_retries=2)
    page = client.fetch_issue(42_000_000)       # no route: perpetual blank
    assert page.load_error
    assert page.final_id == "42000000"
    assert len(driver.navigations) == 2         # honors max_retries


def test_fetch_revisions_shadow_table(fake_selenium):
    client, driver = make_client(fake_selenium)
    origin = "https://issues.oss-fuzz.com/issues/42000001"
    driver.add_route(origin, loaded_issue_page())
    driver.get(origin)

    long_a = "a" * 40
    long_b = "b" * 40
    shadow = FakeElement("shadow", children=[
        FakeElement("table", children=[
            FakeElement("tr", classes={"body"}, children=[
                FakeElement("td", text="zlib"),
                FakeElement("td", text=f"{long_a}:{long_b}")]),
            FakeElement("tr", classes={"body"}, children=[
                FakeElement("td", text="afl"),
                FakeElement("td", text="v1.2")]),
            FakeElement("tr", classes={"body"}, children=[
                FakeElement("td", text="short-row")]),      # skipped
        ])])
    rev_page = FakeElement("html", children=[
        FakeElement("revisions-info", shadow=shadow)])
    driver.add_route(REV_URL, rev_page)

    table = client.fetch_revisions(REV_URL)
    assert table is not None
    assert table.components == ["zlib", "afl"]
    assert table.revisions == [[long_a, long_b], ["v1.2"]]  # range split
    assert table.buildtime == ["1700", "1800"]              # from ?range=
    assert driver.current_url == origin                      # navigated back


def test_fetch_revisions_failed_page(fake_selenium):
    client, driver = make_client(fake_selenium)
    origin = "https://issues.oss-fuzz.com/issues/42000001"
    driver.add_route(origin, loaded_issue_page())
    driver.get(origin)
    driver.add_route(REV_URL, FakeElement("html", children=[
        FakeElement("div", text="Failed to get component revisions.")]))
    assert client.fetch_revisions(REV_URL) is None
