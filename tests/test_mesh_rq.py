"""Mesh-vs-single-device bit-parity for every parallel/rq_mesh helper.

This is the file rq_mesh.py's docstring promises: each sharded reduction is
asserted bit-identical to its single-device twin (the design contract —
float reductions stay device-local, only integer partials cross the mesh),
and the hand-rolled float64 nanpercentile is checked against
``np.nanpercentile`` on adversarial NaN/inf/degenerate inputs.  Runs on the
8 virtual CPU devices conftest.py forces; mesh sizes 8 and 3 cover both the
even and the padded shard layouts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.config import Config
from tse1m_tpu.data.columnar import StudyArrays
from tse1m_tpu.ops.segment import (masked_mean, masked_percentile,
                                   masked_spearman, segment_searchsorted)
from tse1m_tpu.parallel import rq_mesh
from tse1m_tpu.parallel.mesh import make_mesh

LIMIT = "2026-01-01"


@pytest.fixture(scope="module", params=[8, 3])
def mesh(request):
    return make_mesh(request.param)


@pytest.fixture(scope="module")
def arrays(study_db):
    cfg = Config(engine="sqlite", sqlite_path=study_db.config.sqlite_path,
                 limit_date=LIMIT)
    return StudyArrays.from_db(study_db, cfg)


@pytest.fixture(scope="module")
def limit_ns():
    return int(np.datetime64(LIMIT, "ns").astype(np.int64))


def ragged(rng, rows, cols, frac_valid=0.7):
    x = rng.normal(50.0, 20.0, size=(rows, cols)).astype(np.float32)
    mask = rng.random((rows, cols)) < frac_valid
    mask[rng.integers(0, rows)] = False          # one fully-empty row
    if rows > 1:
        mask[rng.integers(0, rows)] = True       # one fully-dense row
    return x, mask


def test_auto_mesh_spans_all_devices():
    m = rq_mesh.auto_mesh()
    assert m is not None and m.devices.size == jax.device_count() == 8


def test_percentile_by_session_mesh_bit_parity(mesh):
    rng = np.random.default_rng(11)
    cols, colmask = ragged(rng, rows=37, cols=16)   # 37 % 8 != 0: padding
    q = np.array([5.0, 25.0, 50.0, 75.0, 95.0], dtype=np.float32)
    got = rq_mesh.percentile_by_session_mesh(cols, colmask, q, mesh)
    want = np.asarray(masked_percentile(jnp.asarray(cols),
                                        jnp.asarray(colmask), q),
                      dtype=np.float64)
    np.testing.assert_array_equal(got, want)
    assert got.shape == (5, 37)


def test_mean_by_session_mesh_bit_parity(mesh):
    rng = np.random.default_rng(12)
    cols, colmask = ragged(rng, rows=41, cols=9)
    got = rq_mesh.mean_by_session_mesh(cols, colmask, mesh)
    want = np.asarray(masked_mean(jnp.asarray(cols), jnp.asarray(colmask)),
                      dtype=np.float64)
    np.testing.assert_array_equal(got, want)


def test_counts_by_project_psum_exact(mesh):
    rng = np.random.default_rng(13)
    mask = rng.random((29, 14)) < 0.4
    got = rq_mesh.counts_by_project_psum(mask, mesh)
    np.testing.assert_array_equal(got, mask.sum(axis=0))


def test_spearman_by_project_mesh_bit_parity(mesh):
    rng = np.random.default_rng(14)
    matrix, mask = ragged(rng, rows=27, cols=40)
    got = rq_mesh.spearman_by_project_mesh(matrix, mask, mesh)
    want = np.asarray(masked_spearman(jnp.asarray(matrix), jnp.asarray(mask)),
                      dtype=np.float64)
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# nanpercentile_by_session_mesh vs the np.nanpercentile oracle
# ---------------------------------------------------------------------------

Q_GRID = np.array([0.0, 25.0, 50.0, 75.0, 90.0, 100.0])


def _oracle(sub, q):
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        return np.nanpercentile(sub, np.atleast_1d(q), axis=0)


def test_nanpercentile_mesh_random_nan_heavy(mesh):
    rng = np.random.default_rng(15)
    sub = rng.normal(0.0, 100.0, size=(23, 53))
    sub[rng.random(sub.shape) < 0.5] = np.nan
    got = rq_mesh.nanpercentile_by_session_mesh(sub, Q_GRID, mesh)
    np.testing.assert_array_equal(got, _oracle(sub, Q_GRID))


def test_nanpercentile_mesh_adversarial_columns(mesh):
    """All-NaN columns, n=1 columns, constant columns, denormal-scale
    values — every column shape the RQ4b trend matrix can produce."""
    rng = np.random.default_rng(16)
    sub = rng.normal(0.0, 1.0, size=(7, 19))
    sub[:, 0] = np.nan                 # all-NaN session
    sub[1:, 1] = np.nan                # single-value session
    sub[:, 2] = 3.25                   # constant session
    sub[:3, 3] = 1e-300                # subnormal-adjacent magnitudes
    sub[3:, 3] = np.nan
    got = rq_mesh.nanpercentile_by_session_mesh(sub, Q_GRID, mesh)
    np.testing.assert_array_equal(got, _oracle(sub, Q_GRID))


def test_nanpercentile_mesh_posinf_routes_to_host(mesh):
    """+inf collides with the device sort fill, so the guard must route to
    host np.nanpercentile — values still match the oracle exactly."""
    rng = np.random.default_rng(17)
    sub = rng.normal(0.0, 1.0, size=(5, 11))
    sub[2, 4] = np.inf
    sub[0, 7] = np.nan
    got = rq_mesh.nanpercentile_by_session_mesh(sub, Q_GRID, mesh)
    np.testing.assert_array_equal(got, _oracle(sub, Q_GRID))


def test_nanpercentile_mesh_neginf_on_device(mesh):
    """-inf does NOT collide with the +inf sort fill and stays on device."""
    rng = np.random.default_rng(18)
    sub = rng.normal(0.0, 1.0, size=(6, 13))
    sub[1, 3] = -np.inf
    sub[4, 9] = np.nan
    got = rq_mesh.nanpercentile_by_session_mesh(sub, Q_GRID, mesh)
    np.testing.assert_array_equal(got, _oracle(sub, Q_GRID))


def test_nanpercentile_mesh_empty_inputs(mesh):
    got = rq_mesh.nanpercentile_by_session_mesh(
        np.empty((0, 5)), Q_GRID, mesh)
    assert got.shape == (Q_GRID.size, 5) and np.isnan(got).all()
    got = rq_mesh.nanpercentile_by_session_mesh(
        np.empty((4, 0)), Q_GRID, mesh)
    assert got.shape == (Q_GRID.size, 0)


def test_nanpercentile_mesh_scalar_q(mesh):
    rng = np.random.default_rng(19)
    sub = rng.normal(size=(9, 10))
    sub[rng.random(sub.shape) < 0.3] = np.nan
    got = rq_mesh.nanpercentile_by_session_mesh(sub, 50.0, mesh)
    np.testing.assert_array_equal(got, _oracle(sub, 50.0))


# ---------------------------------------------------------------------------
# rq1_kernel_mesh vs the single-device _rq1_kernel through the backend
# ---------------------------------------------------------------------------

def _assert_rq1_equal(a, b):
    for f in ("iterations", "total_projects", "detected_counts",
              "iteration_of_issue", "link_idx"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f), err_msg=f)


def test_rq1_mesh_vs_single_device(arrays, limit_ns, mesh):
    """The issue axis rarely divides the device count — the synth study's
    issue total exercises the padded-shard path of rq1_kernel_mesh."""
    res_mesh = JaxBackend(mesh=mesh).rq1_detection(arrays, limit_ns,
                                                   min_projects=2)
    res_one = JaxBackend(mesh=None).rq1_detection(arrays, limit_ns,
                                                  min_projects=2)
    _assert_rq1_equal(res_mesh, res_one)


def test_rq2_trends_mesh_vs_single_device(arrays, limit_ns, mesh):
    res_mesh = JaxBackend(mesh=mesh).rq2_trends(arrays, limit_ns)
    res_one = JaxBackend(mesh=None).rq2_trends(arrays, limit_ns)
    for f in ("matrix", "mask", "spearman", "percentiles", "mean", "counts"):
        np.testing.assert_array_equal(getattr(res_mesh, f),
                                      getattr(res_one, f), err_msg=f)


def test_rq4b_trends_mesh_vs_single_device(arrays, limit_ns, mesh):
    rng = np.random.default_rng(20)
    perm = rng.permutation(arrays.n_projects)
    g1, g2 = np.sort(perm[:6]), np.sort(perm[6:12])
    res_mesh = JaxBackend(mesh=mesh).rq4b_group_trends(
        arrays, limit_ns, g1, g2)
    res_one = JaxBackend(mesh=None).rq4b_group_trends(
        arrays, limit_ns, g1, g2)
    for f in ("matrix", "mask", "g1_percentiles", "g1_counts",
              "g2_percentiles", "g2_counts"):
        np.testing.assert_array_equal(getattr(res_mesh, f),
                                      getattr(res_one, f), err_msg=f)


def test_mesh_parity_vs_pandas_oracle(arrays, limit_ns):
    """Transitive closure: the mesh path equals the pandas reference
    semantics directly, not just the other jax branch."""
    m = rq_mesh.auto_mesh()
    assert m is not None
    res_mesh = JaxBackend(mesh=m).rq1_detection(arrays, limit_ns,
                                                min_projects=2)
    res_pd = PandasBackend().rq1_detection(arrays, limit_ns, min_projects=2)
    _assert_rq1_equal(res_mesh, res_pd)


def test_rq3_mesh_vs_single_device(arrays, limit_ns, mesh):
    """RQ3's three per-issue scans now run through
    segment_searchsorted_mesh when a mesh is active — every field of the
    result must be bit-equal to the single-device path."""
    res_mesh = JaxBackend(mesh=mesh).rq3_coverage_at_detection(arrays,
                                                               limit_ns)
    res_one = JaxBackend(mesh=None).rq3_coverage_at_detection(arrays,
                                                              limit_ns)
    for f in ("det_diff_percent", "det_diff_covered", "det_diff_total",
              "det_project_idx", "det_issue_idx", "det_issue_time_ns",
              "nondet_diff_percent", "nondet_diff_covered",
              "nondet_diff_total", "nondet_project_idx"):
        np.testing.assert_array_equal(getattr(res_mesh, f),
                                      getattr(res_one, f), err_msg=f)


def test_rq4a_mesh_vs_single_device(arrays, limit_ns, mesh):
    rng = np.random.default_rng(21)
    perm = rng.permutation(arrays.n_projects)
    g1, g2 = np.sort(perm[:6]), np.sort(perm[6:12])
    res_mesh = JaxBackend(mesh=mesh).rq4a_detection_trend(
        arrays, limit_ns, g1, g2, min_projects=2)
    res_one = JaxBackend(mesh=None).rq4a_detection_trend(
        arrays, limit_ns, g1, g2, min_projects=2)
    for f in ("iterations", "g1_total", "g1_detected", "g2_total",
              "g2_detected"):
        np.testing.assert_array_equal(getattr(res_mesh, f),
                                      getattr(res_one, f), err_msg=f)


def test_segment_searchsorted_mesh_direct(mesh):
    """Direct oracle test incl. a query count that doesn't divide the
    device count (padded-shard path) and empty inputs."""
    rng = np.random.default_rng(33)
    P = 5
    counts = rng.integers(0, 40, size=P)
    off = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    vals = np.sort(rng.integers(0, 1000, size=off[-1]).astype(np.int32))
    vals = np.concatenate([np.sort(vals[a:b]) for a, b in zip(off, off[1:])])
    vals_lo = rng.integers(0, 10, size=off[-1]).astype(np.int32)
    vals_lo = np.concatenate(  # keep (hi, lo) lexicographically sorted
        [np.sort(vals_lo[a:b]) for a, b in zip(off, off[1:])])
    q = 101  # does not divide 8
    seg = rng.integers(0, P, size=q).astype(np.int32)
    queries = rng.integers(0, 1000, size=q).astype(np.int32)
    queries_lo = rng.integers(0, 10, size=q).astype(np.int32)
    for side in ("left", "right"):
        got = rq_mesh.segment_searchsorted_mesh(
            mesh, vals, off, queries, seg, side, vals_lo, queries_lo)
        exp = np.asarray(segment_searchsorted(
            jnp.asarray(vals), jnp.asarray(off, jnp.int32),
            jnp.asarray(queries), jnp.asarray(seg), side=side,
            values_lo=jnp.asarray(vals_lo),
            queries_lo=jnp.asarray(queries_lo)))
        np.testing.assert_array_equal(got, exp, err_msg=side)
    # Empty queries / empty values degrade to zeros.
    assert rq_mesh.segment_searchsorted_mesh(
        mesh, vals, off, np.empty(0, np.int32), np.empty(0, np.int32),
        "left", vals_lo, np.empty(0, np.int32)).size == 0


def test_rq2_changepoints_mesh_vs_single_device(arrays, limit_ns, mesh):
    res_mesh = JaxBackend(mesh=mesh).rq2_change_points(arrays, limit_ns)
    res_one = JaxBackend(mesh=None).rq2_change_points(arrays, limit_ns)
    for f in ("project_idx", "end_i", "start_ip1", "covered_i", "total_i",
              "covered_ip1", "total_ip1"):
        np.testing.assert_array_equal(getattr(res_mesh, f),
                                      getattr(res_one, f), err_msg=f)
