"""Pinned-value golden regression (tests/goldens/): the frozen-seed synth
study's six RQ artifact CSVs must reproduce the committed values on BOTH
engines — the rebuild's analogue of the reference's published-numbers
oracle (rq1_detection_rate.py:354-412), catching numeric drift that
test_golden_format.py's shape/format checks cannot."""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pandas as pd
import pytest

_GEN = os.path.join(os.path.dirname(__file__), "goldens",
                    "generate_goldens.py")
spec = importlib.util.spec_from_file_location("generate_goldens", _GEN)
gen = importlib.util.module_from_spec(spec)
spec.loader.exec_module(gen)


def _compare_csv(got_path: str, want_path: str, rel: str) -> None:
    got = pd.read_csv(got_path)
    want = pd.read_csv(want_path)
    assert list(got.columns) == list(want.columns), rel
    assert len(got) == len(want), rel
    for col in want.columns:
        g, w = got[col], want[col]
        if pd.api.types.is_float_dtype(w):
            # The device engine's rq2-trend percentiles sort in float32;
            # everything else is bit-exact.  2e-5 relative is the same
            # tolerance bench.py's cross-engine parity gate uses.
            np.testing.assert_allclose(
                g.to_numpy(dtype=np.float64), w.to_numpy(dtype=np.float64),
                rtol=2e-5, atol=2e-5, equal_nan=True,
                err_msg=f"{rel}:{col}")
        else:
            np.testing.assert_array_equal(g.fillna("").to_numpy(),
                                          w.fillna("").to_numpy(),
                                          err_msg=f"{rel}:{col}")


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["pandas", "jax_tpu"])
def test_frozen_study_reproduces_golden_values(backend, tmp_path):
    result = str(tmp_path / "result")
    gen.run_frozen_study(result, backend, str(tmp_path))
    for rel in gen.FILES:
        got = os.path.join(result, rel)
        want = os.path.join(gen.GOLDEN_DIR, rel)
        assert os.path.exists(got), f"artifact missing: {rel}"
        assert os.path.exists(want), (
            f"golden missing: {rel} — run python {_GEN}")
        _compare_csv(got, want, rel)
