"""C7 issue scraping logic, C8 corpus archaeology, normalization adapters,
and the collector->ingest->columnar->RQ1 round trip."""

import json
import os
import subprocess

import numpy as np
import pandas as pd
import pytest

from tse1m_tpu.collect.corpus import (GitHubMergeTimeResolver,
                                      analyze_repository,
                                      run_corpus_collector)
from tse1m_tpu.collect.issues import (IssueEvent, RawIssuePage, RevisionTable,
                                      assemble_issue_record,
                                      extract_fixed_from_events, issue_url,
                                      merge_window_csvs, parse_description,
                                      plan_run, run_scraper_window,
                                      save_issue_batch, scrape_issues,
                                      select_rescrape_ids,
                                      split_revision_range)
from tse1m_tpu.collect.normalize import (buildlog_table_rows,
                                         coverage_table_rows,
                                         issue_table_rows)
from tse1m_tpu.collect.transport import Response

SHA_A = "a" * 40
SHA_B = "b" * 40

DESCRIPTION = """\
Detailed Report: https://oss-fuzz.com/testcase?key=123

Project: zlib
Fuzzing Engine: libFuzzer
Fuzz Target: compress_fuzzer
Job Type: libfuzzer_asan_zlib
Platform Id: linux

Crash Type: Heap-buffer-overflow
Crash Address: 0x60200000eff0
Crash State:
  inflate
  inflateInit2_
  compress_fuzzer

Sanitizer: address (ASAN)
Recommended Security Severity: Medium

Regressed: https://oss-fuzz.com/revisions?job=libfuzzer_asan_zlib&range=1111:2222 extra-tail
Minimized Testcase (1.23 Kb): https://oss-fuzz.com/download?testcase_id=5

Issue filed automatically.
See https://google.github.io/oss-fuzz/ for more information.
"""


def test_issue_url_routing():
    assert "bugs.chromium.org" in issue_url(9_999_999)
    assert "issues.oss-fuzz.com" in issue_url(10_000_000)


def test_split_revision_range():
    assert split_revision_range(f"{SHA_A}:{SHA_B}") == [SHA_A, SHA_B]
    assert split_revision_range("v1.2:3") == ["v1.2:3"]
    assert split_revision_range(SHA_A) == [SHA_A]


def test_parse_description_keys_continuations_and_urls():
    d = parse_description(DESCRIPTION)
    assert d["Project"] == "zlib"
    assert d["Crash Type"] == "Heap-buffer-overflow"
    # multi-line continuation -> list (5_…py:261-267)
    assert d["Crash State"] == ["inflate", "inflateInit2_", "compress_fuzzer"]
    # URL keys keep only the URL token (5_…py:254-257)
    assert d["Regressed"].endswith("range=1111:2222")
    # parenthesised size must not defeat the label (5_…py:245)
    assert d["Minimized Testcase"].endswith("testcase_id=5")
    assert d["Recommended Security Severity"] == "Medium"
    # boilerplate never leaks into values
    assert not any("oss-fuzz" in str(v) and "github.io" in str(v)
                   for v in d.values())


def test_extract_fixed_from_events():
    events = [
        IssueEvent(text="filed", time_iso="2024-01-01T00:00:00Z"),
        IssueEvent(text="Fixed: https://oss-fuzz.com/revisions?range=3:4\nmore",
                   time_iso="2024-02-01T00:00:00Z"),
        IssueEvent(text="unrelated comment", time_iso="2024-03-01T00:00:00Z"),
    ]
    url, t = extract_fixed_from_events(events)
    assert url == "https://oss-fuzz.com/revisions?range=3:4"
    assert t == "2024-02-01T00:00:00Z"
    verified = [IssueEvent(
        text="ClusterFuzz testcase 123 is verified as fixed in\nrange",
        time_iso="2024-04-01T00:00:00Z",
        revision_links=["https://oss-fuzz.com/revisions?range=5:6"])]
    url2, t2 = extract_fixed_from_events(verified)
    assert url2.endswith("range=5:6") and t2.startswith("2024-04")
    assert extract_fixed_from_events([]) == (None, None)


class FakeClient:
    """Offline IssuePageClient over canned pages/revision tables."""

    def __init__(self, pages, revisions=None, fail_ids=()):
        self.pages = pages
        self.revisions = revisions or {}
        self.fail_ids = set(fail_ids)
        self.closed = 0

    def fetch_issue(self, issue_no):
        if issue_no in self.fail_ids:
            raise RuntimeError(f"browser crashed on {issue_no}")
        return self.pages[issue_no]

    def fetch_revisions(self, url):
        return self.revisions.get(url)

    def close(self):
        self.closed += 1


def _page(issue_no, project="zlib"):
    return RawIssuePage(
        final_id=str(issue_no), url=issue_url(issue_no),
        title=f"Issue {issue_no} in {project}: crash",
        reported_time_iso="2024-05-02T11:30:00Z",
        metadata={"Status": "Fixed (Verified)", "Type": "Vulnerability",
                  "Severity": "S2", "Reported": "2024-05-02",
                  "Assignee": None},
        events=[IssueEvent(
            text=f"Fixed: https://oss-fuzz.com/revisions?range={SHA_A}:{SHA_B}",
            time_iso="2024-05-20T09:00:00Z")],
        description=DESCRIPTION.replace("zlib", project),
    )


def _revision_tables():
    url = "https://oss-fuzz.com/revisions?job=libfuzzer_asan_zlib&range=1111:2222"
    return {url: RevisionTable(components=["zlib"],
                               revisions=[[SHA_A, SHA_B]],
                               buildtime=["1111", "2222"])}


def test_assemble_issue_record():
    client = FakeClient({42: _page(42)}, _revision_tables())
    rec = assemble_issue_record(client.fetch_issue(42), client)
    assert rec["id"] == "42"
    assert rec["reported_time"] == "2024-05-02 11:30"
    assert rec["Metadata_Reported_Date"] == "2024-05-02"
    assert rec["Status"] == "Fixed (Verified)"
    assert rec["Fixed"].endswith(f"{SHA_A}:{SHA_B}")
    assert rec["fixed_time"] == "2024-05-20 09:00"
    assert rec["regressed_components"] == ["zlib"]
    assert rec["regressed_revisions"] == [[SHA_A, SHA_B]]
    assert rec["regressed_buildtime"] == ["1111", "2222"]


def test_load_error_page_short_record():
    page = RawIssuePage(final_id="7", url=issue_url(7), load_error=True)
    rec = assemble_issue_record(page, FakeClient({}))
    assert rec["error"] is True and rec["title"] == "Failed to load page"


def test_window_checkpoints_and_recovers(tmp_path):
    ids = [101, 102, 103, 104]
    pages = {i: _page(i) for i in ids}
    made = []

    def factory():
        c = FakeClient(pages, _revision_tables(), fail_ids={102})
        made.append(c)
        return c

    done = run_scraper_window(factory, ids, 0, str(tmp_path), save_interval=2)
    assert done == 3                      # 102 lost to the crash
    assert len(made) == 2                 # client restarted (5_…py:328-332)
    assert made[0].closed == 1
    out = tmp_path / "window_0"
    files = sorted(os.listdir(out))
    assert files == ["001.csv", "002.csv"]
    ids_seen = set()
    for f in files:
        ids_seen |= {json.loads(v) for v in pd.read_csv(out / f)["id"]}
    assert ids_seen == {"101", "103", "104"}


def test_scrape_issues_inline_windows_disjoint_dirs(tmp_path):
    ids = list(range(200, 206))
    pages = {i: _page(i) for i in ids}
    scrape_issues(lambda: FakeClient(pages), ids, str(tmp_path),
                  num_workers=3, parallel=False)
    dirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("window_"))
    assert dirs == ["window_0", "window_1", "window_2"]
    merged = tmp_path / "merged_output.csv"
    assert merge_window_csvs(str(tmp_path), str(merged)) == 6


def test_plan_run_resume_and_rescrape(tmp_path):
    results = tmp_path / "results"
    save_issue_batch([{"id": "300", "Status": "Fixed"},
                      {"id": "301", "Status": None}], str(results / "w0"), 1)
    merged = tmp_path / "merged.csv"
    merge_window_csvs(str(results), str(merged))
    plan = plan_run({300, 301, 302}, str(results))
    assert plan == [302]
    # DSL: Status missing -> re-scrape 301 (5_…py:419-422)
    plan2 = plan_run({300, 301, 302}, str(results), str(merged),
                     {"Status": True})
    assert plan2 == [302, 301]
    df = pd.read_csv(merged)
    assert select_rescrape_ids(df, {"Status": "fixed"}) == [300]
    assert select_rescrape_ids(df, {"Status": False}) == [300]


# -- C8: corpus ---------------------------------------------------------------

class FakeGitHub:
    def __init__(self, merged_at):
        self.merged_at = merged_at

    def get(self, url, params=None):
        if url.endswith("/pulls") and "commits" in url:
            body = [{"number": 77}]
        elif url.endswith("/pulls/77"):
            body = {"merged_at": self.merged_at}
        else:
            return None
        return Response(url=url, status=200, content=json.dumps(body).encode())


def test_corpus_analysis(oss_fuzz_repo):
    resolver = GitHubMergeTimeResolver(
        fetcher=FakeGitHub("2021-04-16T12:00:00Z"), token="t")
    df = analyze_repository(oss_fuzz_repo, ["brotli", "zlib", "ghost"],
                            resolver)
    assert list(df["project_name"]) == ["brotli", "zlib"]  # ghost skipped
    z = df[df["project_name"] == "zlib"].iloc[0]
    assert bool(z["is_Corpus"])
    # corpus landed 45 days after creation (fixture commits)
    assert z["time_elapsed_seconds"] == pytest.approx(45 * 86400)
    assert z["merged_time_elapsed_seconds"] == pytest.approx(
        (46 * 86400) + 2 * 3600)
    b = df[df["project_name"] == "brotli"].iloc[0]
    assert bool(b["is_Corpus"]) and b["time_elapsed_seconds"] == 0.0


def test_corpus_collector_skips_existing(oss_fuzz_repo, tmp_path):
    out = tmp_path / "project_corpus_analysis.csv"
    df1 = run_corpus_collector(oss_fuzz_repo, str(out))
    assert out.exists() and len(df1) == 2
    # git history untouched; cached CSV served (user_corpus.py:367-370)
    df2 = run_corpus_collector(oss_fuzz_repo, str(out))
    assert len(df2) == 2


def test_corpus_groups_accept_collector_csv(oss_fuzz_repo, tmp_path):
    """The collection half's CSV feeds the analysis half unchanged."""
    from tse1m_tpu.analysis.corpus import load_corpus_groups

    out = tmp_path / "c.csv"
    run_corpus_collector(oss_fuzz_repo, str(out))
    groups = load_corpus_groups(str(out), {"brotli", "zlib", "other"})
    assert "brotli" in groups.groups["group2"]   # corpus at creation
    assert "zlib" in groups.groups["group4"]     # 45 days later
    assert "other" in groups.groups["group1"]    # absent from CSV


# -- normalization + round trip ----------------------------------------------

def test_issue_table_rows():
    records = [assemble_issue_record(_page(i, project="zlib"),
                                     FakeClient({}, _revision_tables()))
               for i in (42, 43)]
    records.append({"id": "99", "error": True,
                    "title": "Failed to load page"})
    df = pd.DataFrame([{k: json.dumps(v, ensure_ascii=False)
                        for k, v in r.items()} for r in records])
    table = issue_table_rows(df)
    assert len(table) == 2                      # error row dropped
    row = table.iloc[0]
    assert row["project"] == "zlib"
    assert row["rts"] == "2024-05-02 11:30"
    assert row["status"] == "Fixed (Verified)"
    assert row["crash_type"] == "Heap-buffer-overflow"
    assert row["severity"] == "S2"
    assert row["regressed_build"] == "{" + SHA_A + "," + SHA_B + "}"


def test_round_trip_collectors_to_rq1(tmp_path, oss_fuzz_repo):
    """Collector outputs -> normalize -> ingest_csv_dir -> StudyArrays ->
    RQ1 kernel: proves the layer feeds the analysis engine end to end."""
    from tse1m_tpu.backend.pandas_backend import PandasBackend
    from tse1m_tpu.collect.buildlogs import parse_build_log
    from tse1m_tpu.collect.projects import collect_project_info
    from tse1m_tpu.config import Config
    from tse1m_tpu.data.columnar import StudyArrays
    from tse1m_tpu.db.connection import DB
    from tse1m_tpu.db.ingest import ingest_csv_dir
    from tests.test_collect import FUZZ_LOG, COVERAGE_LOG

    csv_dir = tmp_path / "csv"
    csv_dir.mkdir()

    collect_project_info(oss_fuzz_repo).to_csv(csv_dir / "project_info.csv",
                                               index=False)

    analyzed = []
    base = pd.Timestamp("2024-05-01 10:00:00")
    for i in range(30):
        rec = parse_build_log(f"b{i}", FUZZ_LOG if i % 3 else COVERAGE_LOG)
        analyzed.append({
            "id": rec.build_id, "project": rec.project,
            "build_type": rec.build_type, "result": rec.result,
            "timecreated": str(base + pd.Timedelta(hours=i)),
            "modules": json.dumps(rec.modules),
            "revisions": json.dumps(rec.revisions),
        })
    buildlog_table_rows(pd.DataFrame(analyzed)).to_csv(
        csv_dir / "buildlog_data.csv", index=False)

    cov = pd.DataFrame({
        "date": [f"202405{d:02d}" for d in range(1, 11)],
        "project": ["zlib"] * 10,
        "coverage": np.linspace(50, 60, 10),
        "covered_line": np.linspace(500, 600, 10),
        "total_line": [1000.0] * 10,
        "exist": [True] * 10,
    })
    coverage_table_rows(cov).to_csv(csv_dir / "total_coverage.csv",
                                    index=False)

    issue_records = [assemble_issue_record(_page(500 + i),
                                           FakeClient({}, _revision_tables()))
                     for i in range(3)]
    issues_df = pd.DataFrame([{k: json.dumps(v, ensure_ascii=False)
                               for k, v in r.items()}
                              for r in issue_records])
    issue_table_rows(issues_df).to_csv(csv_dir / "issues.csv", index=False)

    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / "rt.sqlite"),
                 limit_date="2026-01-01")
    db = DB(config=cfg).connect()
    try:
        counts = ingest_csv_dir(db, str(csv_dir))
        assert counts["buildlog_data"] == 30
        assert counts["issues"] == 3
        arrays = StudyArrays.from_db(db, cfg, projects=["zlib"])
        limit_ns = int(np.datetime64("2026-01-01", "ns").astype(np.int64))
        res = PandasBackend().rq1_detection(arrays, limit_ns, min_projects=1)
        assert res.total_projects.size > 0
        assert res.iteration_of_issue.size == arrays.issues.counts().sum()
    finally:
        db.closeConnection()
