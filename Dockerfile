# Research container for the TPU rebuild (reference Dockerfile:1-35: a
# python-slim + uv image whose CMD idles so `docker compose run research`
# can exec the analysis).  CPU jax runs everything here — including the
# 8-virtual-device mesh tests; on a TPU VM swap in the jax[tpu] wheel
# (see requirements.txt) and run outside docker-compose's db harness.
FROM python:3.12-slim

COPY --from=ghcr.io/astral-sh/uv:latest /uv /uvx /bin/

WORKDIR /app

# git is needed by the collection layer (project first-commit archaeology,
# corpus `git log -S` analysis); build tools cover sdist fallbacks.
RUN apt-get update && apt-get install -y --no-install-recommends \
    git \
    build-essential \
    && rm -rf /var/lib/apt/lists/*

# Dependencies first so code edits don't bust the layer cache.
COPY requirements.txt /app/
RUN uv pip install --system -r requirements.txt psycopg2-binary pytest

COPY ./tse1m_tpu /app/tse1m_tpu
COPY ./program /app/program
COPY ./tests /app/tests
COPY ./run_all_analysis.sh ./bench.py ./__graft_entry__.py ./pyproject.toml /app/

CMD ["sleep", "infinity"]
