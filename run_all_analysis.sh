#!/bin/bash
# Orchestrator: the TPU rebuild of the reference's run_all_analysis.sh
# (reference run_all_analysis.sh:1-53) — the same six sequential steps over
# the same entry-point paths, `set -e` fail-fast.  The engine behind each
# step is chosen by program/envFile.ini [FRAMEWORK] backend (pandas |
# jax_tpu); TSE1M_BACKEND overrides per run.
#
# The reference assumes a Postgres restored from backup_clean.sql
# (README.md:55).  That dump is not redistributable, so on a clean checkout
# with the sqlite engine this script bootstraps a synthetic study of the
# same shape first (disable with TSE1M_NO_SYNTH=1).

set -e

INI=program/envFile.ini
ENGINE=$(awk -F' *= *' '/^engine/ {print $2}' "$INI")
ENGINE=${TSE1M_ENGINE:-${ENGINE:-sqlite}}
DB_PATH=$(awk -F' *= *' '/^sqlite_path/ {print $2}' "$INI")
DB_PATH=${TSE1M_SQLITE_PATH:-${DB_PATH:-data/database/tse1m.sqlite}}

if [ "$ENGINE" = "sqlite" ] && [ ! -f "$DB_PATH" ] && [ -z "$TSE1M_NO_SYNTH" ]; then
    echo "No study database at $DB_PATH - generating a synthetic study"
    echo "(the reference restores backup_clean.sql here; see README)."
    python3 -m tse1m_tpu.cli synth --db "$DB_PATH"
fi

echo "========================================================"
echo "Starting Reproduction of All Research Questions (RQ1-RQ4)"
echo "========================================================"

echo ""
echo "[1/6] Running RQ1: Detection Rate Analysis..."
echo "Executing: python3 program/research_questions/rq1_detection_rate.py"
python3 program/research_questions/rq1_detection_rate.py

echo ""
echo "[2/6] Running RQ2: Coverage and Added Analysis..."
echo "Executing: python3 program/research_questions/rq2_coverage_and_added.py"
python3 program/research_questions/rq2_coverage_and_added.py

echo ""
echo "[3/6] Running RQ2: Coverage Count Analysis..."
echo "Executing: python3 program/research_questions/rq2_coverage_count.py"
python3 program/research_questions/rq2_coverage_count.py

echo ""
echo "[4/6] Running RQ3: Diff Coverage at Detection..."
echo "Executing: python3 program/research_questions/rq3_diff_coverage_at_detection.py"
python3 program/research_questions/rq3_diff_coverage_at_detection.py

echo ""
echo "[5/6] Running RQ4a: Bug Analysis..."
echo "Executing: python3 program/research_questions/rq4a_bug.py"
python3 program/research_questions/rq4a_bug.py

echo ""
echo "[6/6] Running RQ4b: Coverage Analysis..."
echo "Executing: python3 program/research_questions/rq4b_coverage.py"
python3 program/research_questions/rq4b_coverage.py

echo ""
echo "========================================================"
echo "All Research Questions have been reproduced successfully!"
echo "Results are saved in the 'data/result_data' directory."
echo "========================================================"
